"""Core nn layers (Linear/Embedding/Conv/Norm/Dropout/activations/loss).

Reference: python/paddle/nn/layer/{common,conv,norm,activation,loss}.py.
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Parameter, Tensor
from . import functional as F
from . import initializer as I
from .layer import Layer

__all__ = [
    "Linear", "Embedding", "Dropout", "Dropout2D", "Dropout3D", "AlphaDropout",
    "Flatten", "Unflatten", "Pad1D", "Pad2D", "Pad3D", "Upsample",
    "UpsamplingBilinear2D", "UpsamplingNearest2D", "CosineSimilarity",
    "Bilinear", "Identity",
    "Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
    "Conv3DTranspose",
    "LayerNorm", "RMSNorm", "BatchNorm", "BatchNorm1D", "BatchNorm2D",
    "BatchNorm3D", "SyncBatchNorm", "GroupNorm", "InstanceNorm1D",
    "InstanceNorm2D", "InstanceNorm3D", "LocalResponseNorm", "SpectralNorm",
    "ReLU", "ReLU6", "GELU", "SiLU", "Swish", "LeakyReLU", "ELU", "CELU",
    "SELU", "PReLU", "RReLU", "Sigmoid", "LogSigmoid", "Tanh", "Tanhshrink",
    "Hardshrink", "Softshrink", "Hardsigmoid", "Hardswish", "Hardtanh",
    "Mish", "Softmax", "LogSoftmax", "Softplus", "Softsign",
    "ThresholdedReLU", "Maxout", "GLU",
    "MaxPool1D", "MaxPool2D", "MaxPool3D", "AvgPool1D", "AvgPool2D",
    "AvgPool3D", "AdaptiveAvgPool1D", "AdaptiveAvgPool2D", "AdaptiveAvgPool3D",
    "AdaptiveMaxPool1D", "AdaptiveMaxPool2D",
    "CrossEntropyLoss", "MSELoss", "L1Loss", "NLLLoss", "BCELoss",
    "BCEWithLogitsLoss", "KLDivLoss", "SmoothL1Loss", "MarginRankingLoss",
    "CosineEmbeddingLoss", "TripletMarginLoss", "HingeEmbeddingLoss",
    "PixelShuffle",
]


class Identity(Layer):
    def forward(self, x):
        return x


class Linear(Layer):
    """paddle.nn.Linear: weight [in_features, out_features] (x @ W + b)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=I.XavierNormal())
        if bias_attr is False:
            self.bias = None
            self._parameters["bias"] = None
        else:
            self.bias = self.create_parameter((out_features,), attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        if padding_idx is not None:
            self.weight._replace_value(
                self.weight._value.at[padding_idx].set(0.0))

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self.padding_idx)


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training,
                         mode=self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from ..ops.registry import OPS
        return OPS["flatten"](x, self.start_axis, self.stop_axis)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis = axis
        self.shape = shape

    def forward(self, x):
        from ..ops.registry import OPS
        new_shape = list(x.shape)
        new_shape[self.axis:self.axis + 1] = list(self.shape)
        return OPS["reshape"](x, new_shape)


class _PadND(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW"):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        from ..ops.registry import OPS
        return OPS["pad"](x, self.padding, mode=self.mode, value=self.value,
                          data_format=self.data_format)


class Pad1D(_PadND):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL"):
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadND):
    pass


class Pad3D(_PadND):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW"):
        super().__init__(padding, mode, value, data_format)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW"):
        super().__init__(size, scale_factor, "nearest", False, data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW"):
        super().__init__(size, scale_factor, "bilinear", True, data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            (out_features, in1_features, in2_features), attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_features,), is_bias=True, attr=bias_attr)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


# ------------------------------------------------------------- convolution


class _ConvND(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, dims,
                 stride=1, padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 transpose=False, output_padding=0):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * dims
        self._dims = dims
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        self.data_format = data_format
        self._transpose = transpose
        self.output_padding = output_padding
        if transpose:
            wshape = (in_channels, out_channels // groups) + tuple(kernel_size)
        else:
            wshape = (out_channels, in_channels // groups) + tuple(kernel_size)
        fan_in = in_channels
        for k in kernel_size:
            fan_in *= k
        self.weight = self.create_parameter(
            wshape, attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in // groups))
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), is_bias=True, attr=bias_attr)

    def forward(self, x):
        fns = {
            (1, False): F.conv1d, (2, False): F.conv2d, (3, False): F.conv3d,
            (1, True): F.conv1d_transpose, (2, True): F.conv2d_transpose,
            (3, True): F.conv3d_transpose,
        }
        fn = fns[(self._dims, self._transpose)]
        if self._transpose:
            return fn(x, self.weight, self.bias, stride=self.stride,
                      padding=self.padding, output_padding=self.output_padding,
                      dilation=self.dilation, groups=self.groups,
                      data_format=self.data_format)
        return fn(x, self.weight, self.bias, stride=self.stride,
                  padding=self.padding, dilation=self.dilation,
                  groups=self.groups, data_format=self.data_format)


class Conv1D(_ConvND):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)


class Conv2D(_ConvND):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)


class Conv3D(_ConvND):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)


class Conv1DTranspose(_ConvND):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)


class Conv2DTranspose(_ConvND):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)


class Conv3DTranspose(_ConvND):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)


# ------------------------------------------------------------- normalization


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            self.normalized_shape, default_initializer=I.Constant(1.0),
            attr=None if weight_attr is False else weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            self.normalized_shape, is_bias=True, attr=None)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias,
                            self.epsilon)


class RMSNorm(Layer):
    """TPU-first RMSNorm backed by the Pallas kernel (ops/pallas/rms_norm.py)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            (hidden_size,), default_initializer=I.Constant(1.0),
            attr=weight_attr)

    def forward(self, x):
        return F.rms_norm(x, self.weight, self.epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        self.weight = None if weight_attr is False else self.create_parameter(
            (num_features,), default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_features,), is_bias=True)
        from ..ops.creation import zeros, ones
        self.register_buffer("_mean", zeros((num_features,)))
        self.register_buffer("_variance", ones((num_features,)))

    def forward(self, x):
        training = self.training and not self.use_global_stats
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=training,
                            momentum=self.momentum, epsilon=self.epsilon,
                            data_format=self.data_format)


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN: stats all-reduced over the dp mesh axis when inside
    shard_map (parallel/collective.py); identical to BatchNorm on one chip."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, _BatchNormBase) and not isinstance(
                    sub, SyncBatchNorm):
                new = SyncBatchNorm(sub.num_features, sub.momentum,
                                    sub.epsilon, data_format=sub.data_format)
                new.weight = sub.weight
                new.bias = sub.bias
                new._buffers = sub._buffers
                layer._sub_layers[name] = new
            else:
                cls.convert_sync_batchnorm(sub)
        return layer


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.num_groups = num_groups
        self.epsilon = epsilon
        self.data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            (num_channels,), default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_channels,), is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.epsilon, self.weight,
                            self.bias, self.data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            (num_features,), default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_features,), is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self.epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    """paddle.nn.SpectralNorm parity: ``forward(weight)`` returns the
    spectrally-normalized weight, estimating the top singular value by
    power iteration on persistent u/v buffers (reference
    python/paddle/nn/layer/norm.py SpectralNorm / spectral_norm op).
    Stop-gradient through u/v like the reference kernel."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None, dtype="float32"):
        super().__init__(dtype=dtype)
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        self._shape = list(weight_shape)
        h = self._shape[dim]
        w = int(np.prod(self._shape)) // h
        rng = np.random.RandomState(0)
        self.register_buffer("weight_u", Tensor(
            rng.randn(h).astype(dtype)), persistable=True)
        self.register_buffer("weight_v", Tensor(
            rng.randn(w).astype(dtype)), persistable=True)

    def forward(self, weight):
        from ..core.tensor import dispatch, unwrap

        dim, eps, iters = self._dim, self._eps, self._power_iters
        h = self._shape[dim]
        perm = [dim] + [i for i in range(len(self._shape)) if i != dim]

        # power iteration updates the persistent u/v buffers eagerly
        # (stop-gradient side channel, like the reference's in-place
        # u/v); the dispatched op then only computes sigma + division.
        # Under a jit trace the buffers can't be written back (they bake
        # in as constants), so cross-step accumulation is unavailable —
        # compensate with enough iterations for a converged per-step
        # estimate instead of silently keeping a one-step-from-random u.
        w_raw = jax.lax.stop_gradient(unwrap(weight))
        traced = isinstance(w_raw, jax.core.Tracer)
        n_iter = max(iters, 8) if traced else iters
        mat = jnp.transpose(w_raw, perm).reshape(h, -1)
        u, v = unwrap(self.weight_u), unwrap(self.weight_v)
        for _ in range(n_iter):
            v = mat.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + eps)
        if not isinstance(u, jax.core.Tracer):
            self.weight_u.set_value(u)
            self.weight_v.set_value(v)

        def fn(wv, uv, vv):
            m = jnp.transpose(wv, perm).reshape(h, -1)
            sigma = uv @ (m @ vv)
            return wv / sigma

        return dispatch(fn, weight, u, v, name="spectral_norm",
                        nondiff_args=(1, 2))


# ------------------------------------------------------------- activations


def _act_layer(fname, **fixed):
    class _Act(Layer):
        def __init__(self, **kw):
            super().__init__()
            self._kw = {**fixed, **kw}

        def forward(self, x):
            return getattr(F, fname)(x, **self._kw)

        def extra_repr(self):
            return ", ".join(f"{k}={v}" for k, v in self._kw.items())

    _Act.__name__ = fname
    return _Act


ReLU = _act_layer("relu")
ReLU6 = _act_layer("relu6")
GELU = _act_layer("gelu")
SiLU = _act_layer("silu")
Swish = _act_layer("swish")
Mish = _act_layer("mish")
Tanhshrink = _act_layer("tanhshrink")
Hardshrink = _act_layer("hardshrink")
Softshrink = _act_layer("softshrink")
Hardsigmoid = _act_layer("hardsigmoid")
Hardswish = _act_layer("hardswish")
Hardtanh = _act_layer("hardtanh")
Softplus = _act_layer("softplus")
Softsign = _act_layer("softsign")
ThresholdedReLU = _act_layer("thresholded_relu")
LogSoftmax = _act_layer("log_softmax")
Softmax = _act_layer("softmax")
GLU = _act_layer("glu")
ELU = _act_layer("elu")
CELU = _act_layer("celu")
SELU = _act_layer("selu")
LeakyReLU = _act_layer("leaky_relu")
RReLU = _act_layer("rrelu")


class Sigmoid(Layer):
    def forward(self, x):
        from ..ops.registry import OPS
        return OPS["sigmoid"](x)


class LogSigmoid(Layer):
    def forward(self, x):
        from ..ops.registry import OPS
        return OPS["logsigmoid"](x)


class Tanh(Layer):
    def forward(self, x):
        from ..ops.registry import OPS
        return OPS["tanh"](x)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self.data_format = data_format
        self.weight = self.create_parameter(
            (num_parameters,), default_initializer=I.Constant(init),
            attr=weight_attr)

    def forward(self, x):
        return F.prelu(x, self.weight, self.data_format)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups = groups
        self.axis = axis

    def forward(self, x):
        return F.maxout(x, self.groups, self.axis)


# ------------------------------------------------------------- pooling


def _pool_layer(fname):
    class _Pool(Layer):
        def __init__(self, kernel_size, stride=None, padding=0, **kw):
            super().__init__()
            self.kernel_size = kernel_size
            self.stride = stride
            self.padding = padding
            self._kw = kw

        def forward(self, x):
            return getattr(F, fname)(x, self.kernel_size, self.stride,
                                     self.padding, **self._kw)

    _Pool.__name__ = fname
    return _Pool


MaxPool1D = _pool_layer("max_pool1d")
MaxPool2D = _pool_layer("max_pool2d")
MaxPool3D = _pool_layer("max_pool3d")
AvgPool1D = _pool_layer("avg_pool1d")
AvgPool2D = _pool_layer("avg_pool2d")
AvgPool3D = _pool_layer("avg_pool3d")


def _adaptive_pool_layer(fname):
    class _Pool(Layer):
        def __init__(self, output_size, **kw):
            super().__init__()
            self.output_size = output_size
            self._kw = kw

        def forward(self, x):
            return getattr(F, fname)(x, self.output_size, **self._kw)

    _Pool.__name__ = fname
    return _Pool


AdaptiveAvgPool1D = _adaptive_pool_layer("adaptive_avg_pool1d")
AdaptiveAvgPool2D = _adaptive_pool_layer("adaptive_avg_pool2d")
AdaptiveAvgPool3D = _adaptive_pool_layer("adaptive_avg_pool3d")
AdaptiveMaxPool1D = _adaptive_pool_layer("adaptive_max_pool1d")
AdaptiveMaxPool2D = _adaptive_pool_layer("adaptive_max_pool2d")


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


# ------------------------------------------------------------- losses


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True,
                 label_smoothing=0.0, name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.label_smoothing = label_smoothing

    def forward(self, input, label):  # noqa: A002
        return F.cross_entropy(input, label, weight=self.weight,
                               ignore_index=self.ignore_index,
                               reduction=self.reduction,
                               soft_label=self.soft_label, axis=self.axis,
                               label_smoothing=self.label_smoothing)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.nll_loss(input, label, self.weight, self.ignore_index,
                          self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.binary_cross_entropy(input, label, self.weight,
                                      self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None,
                 name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, self.weight, self.reduction, self.pos_weight)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.kl_div(input, label, self.reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):  # noqa: A002
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):  # noqa: A002
        return F.margin_ranking_loss(input, other, label, self.margin,
                                     self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin,
                                       self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.p = p
        self.epsilon = epsilon
        self.swap = swap
        self.reduction = reduction

    def forward(self, input, positive, negative):  # noqa: A002
        return F.triplet_margin_loss(input, positive, negative, self.margin,
                                     self.p, self.epsilon, self.swap,
                                     self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.hinge_embedding_loss(input, label, self.margin,
                                      self.reduction)
