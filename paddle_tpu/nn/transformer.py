"""Transformer layers — MultiHeadAttention, encoder/decoder stacks.

Reference parity: python/paddle/nn/layer/transformer.py
(MultiHeadAttention:88, TransformerEncoderLayer:440,
TransformerEncoder:614, TransformerDecoderLayer:683,
TransformerDecoder:895, Transformer:983). TPU-native: attention is a
single batched einsum pipeline ([B,S,H,D] layout) routed through
F.scaled_dot_product_attention so it picks up the Pallas flash kernel
when no explicit mask/weights are requested; masks follow the reference
convention (bool keep-mask or additive float).
"""
import collections
import copy

import jax
import jax.numpy as jnp

from ..core.tensor import dispatch
from . import functional as F
from .layer import Layer, LayerList
from .layers_basic import Dropout, LayerNorm, Linear

__all__ = [
    "MultiHeadAttention", "TransformerEncoderLayer", "TransformerEncoder",
    "TransformerDecoderLayer", "TransformerDecoder", "Transformer",
]


def _convert_attention_mask(attn_mask, dtype):
    """bool keep-mask → additive float; float passes through
    (transformer.py:36 _convert_attention_mask)."""
    if attn_mask is None:
        return None
    if str(attn_mask.dtype) in ("bool", "paddle.bool"):
        return dispatch(
            lambda m: jnp.where(m, jnp.zeros([], dtype),
                                jnp.full([], -1e9, dtype)),
            attn_mask, name="convert_attn_mask")
    return attn_mask


class MultiHeadAttention(Layer):
    """transformer.py:88. Layout [batch, seq, embed]; heads split inside."""

    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None,
                 vdim=None, need_weights=False, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        self.embed_dim = embed_dim
        self.kdim = kdim or embed_dim
        self.vdim = vdim or embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout = dropout
        self.need_weights = need_weights
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(self.kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(self.vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _split_heads(self, x):
        B, S = x.shape[0], x.shape[1]
        return x.reshape([B, S, self.num_heads, self.head_dim])

    def gen_cache(self, key, value=None, type=None):
        """Reference transformer.py:88 gen_cache: type=StaticCache projects
        the (encoder) key once; (key, value) pair seeds an incremental
        Cache; key alone seeds an empty incremental Cache."""
        if type == MultiHeadAttention.StaticCache:
            value = key if value is None else value
            k = self._split_heads(self.k_proj(key))
            v = self._split_heads(self.v_proj(value))
            return self.StaticCache(k, v)
        if value is not None:
            return self.Cache(key, value)
        # empty incremental decode cache in the layer's compute dtype
        import paddle_tpu as pt
        B = key.shape[0]
        dt = str(self.k_proj.weight.dtype)
        k = pt.zeros([B, 0, self.num_heads, self.head_dim], dtype=dt)
        v = pt.zeros([B, 0, self.num_heads, self.head_dim], dtype=dt)
        return self.Cache(k, v)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        key = query if key is None else key
        value = key if value is None else value
        q = self._split_heads(self.q_proj(query))  # [B,S,H,D]
        if isinstance(cache, self.StaticCache):
            k, v = cache.k, cache.v
        else:
            k = self._split_heads(self.k_proj(key))
            v = self._split_heads(self.v_proj(value))
            if isinstance(cache, self.Cache):
                import paddle_tpu as pt
                k = pt.concat([cache.k, k], axis=1)
                v = pt.concat([cache.v, v], axis=1)
                cache = self.Cache(k, v)

        mask = _convert_attention_mask(attn_mask, jnp.float32)
        if self.need_weights:
            out, weights = self._attn_with_weights(q, k, v, mask)
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=mask, dropout_p=self.dropout,
                training=self.training)
            weights = None
        B, S = out.shape[0], out.shape[1]
        out = out.reshape([B, S, self.embed_dim])
        out = self.out_proj(out)
        outs = [out]
        if self.need_weights:
            outs.append(weights)
        if cache is not None and not isinstance(cache, self.StaticCache):
            outs.append(cache)
        return out if len(outs) == 1 else tuple(outs)

    def _attn_with_weights(self, q, k, v, mask):
        import math as _m
        drop = self.dropout
        training = self.training

        def fn(q, k, v, *m):
            qh, kh, vh = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
            s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) / _m.sqrt(qh.shape[-1])
            if m:
                s = s + m[0]
            p = jax.nn.softmax(s.astype(jnp.float32), -1).astype(qh.dtype)
            o = jnp.einsum("bhqk,bhkd->bhqd", p, vh)
            return jnp.swapaxes(o, 1, 2), p

        args = (q, k, v) + ((mask,) if mask is not None else ())
        out, p = dispatch(fn, *args, name="mha_attention")
        if drop > 0.0 and training:
            out = F.dropout(out, p=drop, training=training)
        return out, p


def _get_activation(name):
    fn = getattr(F, name, None)
    if fn is None:
        raise ValueError(f"unknown activation {name!r}")
    return fn


class TransformerEncoderLayer(Layer):
    """transformer.py:440 — self-attn + FFN with pre/post-norm."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead, dropout=attn_dropout,
            weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                              bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                              bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.activation = _get_activation(activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


class TransformerEncoder(Layer):
    """transformer.py:614 — clones of one encoder layer."""

    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList(
            [encoder_layer] +
            [copy.deepcopy(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, src_mask=src_mask)
            else:
                output, c = mod(output, src_mask=src_mask, cache=cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    """transformer.py:683 — self-attn + cross-attn + FFN."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead, dropout=attn_dropout,
            weight_attr=weight_attr, bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(
            d_model, nhead, dropout=attn_dropout,
            weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                              bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                              bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.activation = _get_activation(activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
            inc_cache, static_cache = None, None
        else:
            inc_cache, static_cache = cache
            tgt, inc_cache = self.self_attn(tgt, tgt, tgt, tgt_mask,
                                            inc_cache)
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if static_cache is not None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask,
                                  static_cache)
        else:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (inc_cache, static_cache))

    def gen_cache(self, memory):
        inc = self.self_attn.gen_cache(memory)
        static = self.cross_attn.gen_cache(
            memory, type=MultiHeadAttention.StaticCache)
        return inc, static


class TransformerDecoder(Layer):
    """transformer.py:895."""

    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList(
            [decoder_layer] +
            [copy.deepcopy(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        output = tgt
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, memory, tgt_mask, memory_mask)
            else:
                output, c = mod(output, memory, tgt_mask, memory_mask,
                                cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        caches = [layer.gen_cache(memory) for layer in self.layers]
        if do_zip:
            caches = list(zip(*caches))
        return caches


class Transformer(Layer):
    """transformer.py:983 — full encoder-decoder."""

    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers,
                                              enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers,
                                              dec_norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask=src_mask)
        output = self.decoder(tgt, memory, tgt_mask=tgt_mask,
                              memory_mask=memory_mask)
        return output

    def generate_square_subsequent_mask(self, length):
        """Additive causal mask [length, length] (transformer.py:1080)."""
        import paddle_tpu as pt
        import numpy as np
        m = np.triu(np.full((length, length), -np.inf, dtype=np.float32), 1)
        return pt.to_tensor(m)
