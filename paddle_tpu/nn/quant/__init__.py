"""paddle.nn.quant parity (reference python/paddle/nn/quant/)."""
from ...quantization import QuantedConv2D, QuantedLinear  # noqa: F401

__all__ = ["Stub"]


class Stub:
    """Reference nn/quant/stub.py Stub: placeholder marking where an
    activation quanter should attach; resolved by QuantConfig during
    quantize()."""

    def __init__(self, observer=None):
        self._observer = observer

    def forward(self, x):
        return x

    __call__ = forward
