"""paddle.nn.quant parity (reference python/paddle/nn/quant/)."""
from ...nn.layer import Layer
from ...quantization import QuantedConv2D, QuantedLinear  # noqa: F401

__all__ = ["Stub"]


class Stub(Layer):
    """Reference nn/quant/stub.py Stub: a Layer placeholder marking where
    an activation quanter should attach; being a Layer it appears in
    named_sublayers() so QuantConfig/quantize() traversal can resolve it."""

    def __init__(self, observer=None):
        super().__init__()
        self._observer = observer

    def forward(self, x):
        return x
