"""Layer wrappers for the round-3 functional tail + seq2seq decoding.

Reference: python/paddle/nn/layer/{common,loss,pooling,vision}.py tail and
python/paddle/nn/decode.py (dynamic_decode/BeamSearchDecoder).
"""
from __future__ import annotations

import numpy as np

from . import functional as F
from .layer import Layer

__all__ = ["Unfold", "Fold", "PairwiseDistance", "Softmax2D", "Silu",
           "CTCLoss", "RNNTLoss", "HSigmoidLoss", "PixelUnshuffle",
           "ChannelShuffle", "ZeroPad2D", "MaxUnPool1D", "MaxUnPool2D",
           "MaxUnPool3D", "MultiLabelSoftMarginLoss", "MultiMarginLoss",
           "TripletMarginWithDistanceLoss", "SoftMarginLoss",
           "AdaptiveMaxPool3D", "BeamSearchDecoder", "dynamic_decode"]


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return F.unfold(x, self.kernel_sizes, self.strides, self.paddings,
                        self.dilations)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.output_sizes = output_sizes
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return F.fold(x, self.output_sizes, self.kernel_sizes,
                      self.strides, self.paddings, self.dilations)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


class Softmax2D(Layer):
    """Softmax over the channel dim of NCHW input."""

    def forward(self, x):
        return F.softmax(x, axis=-3)


class Silu(Layer):
    def forward(self, x):
        return F.silu(x)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor = downscale_factor

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups

    def forward(self, x):
        return F.channel_shuffle(x, self.groups)


class ZeroPad2D(Layer):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__()
        self.padding = padding
        self.data_format = data_format

    def forward(self, x):
        return F.zeropad2d(x, self.padding, self.data_format)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool1d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.output_size)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool2d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.output_size)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.output_size)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size, self.return_mask)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction, norm_by_times)


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):  # noqa: A002
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           self.blank, self.fastemit_lambda, self.reduction)


class HSigmoidLoss(Layer):
    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        self.num_classes = num_classes
        self.weight = self.create_parameter(
            (num_classes - 1, feature_size), attr=weight_attr)
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter((num_classes - 1, 1),
                                              attr=bias_attr, is_bias=True)

    def forward(self, input, label):  # noqa: A002
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.multi_label_soft_margin_loss(input, label, self.weight,
                                              self.reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.p = p
        self.margin = margin
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.multi_margin_loss(input, label, self.p, self.margin,
                                   self.weight, self.reduction)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.soft_margin_loss(input, label, self.reduction)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.distance_function = distance_function
        self.margin = margin
        self.swap = swap
        self.reduction = reduction

    def forward(self, input, positive, negative):  # noqa: A002
        return F.triplet_margin_with_distance_loss(
            input, positive, negative, self.distance_function, self.margin,
            self.swap, self.reduction)


# ------------------------------------------------------ seq2seq decoding


class Decoder:
    """Abstract decoder interface (reference python/paddle/nn/decode.py:
    Decoder.initialize/step/finalize)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        return outputs, final_states


class BeamSearchDecoder(Decoder):
    """Beam search over an RNN cell (reference decode.py BeamSearchDecoder).

    cell: an RNNCellBase-like layer (call -> (output, new_state));
    embedding_fn maps token ids -> embeddings; output layer projects cell
    output to vocab logits.
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # host-side numpy beam search (decode loops are data-dependent; the
    # reference's while_op loop is likewise dynamic)
    def _logits(self, ids, states):
        import paddle_tpu as pt
        emb = self.embedding_fn(pt.to_tensor(ids)) \
            if self.embedding_fn is not None else pt.to_tensor(ids)
        out, new_states = self.cell(emb, states)
        if self.output_fn is not None:
            out = self.output_fn(out)
        return out, new_states


def dynamic_decode(decoder, inits=None, max_step_num=32, **kwargs):
    """Greedy/beam decode loop (reference decode.py dynamic_decode).

    Returns (ids [B, beam, T], sequence_lengths [B, beam]).
    """
    import jax.numpy as jnp

    import paddle_tpu as pt
    from ..core.tensor import unwrap

    cell_states = decoder.cell.get_initial_states(inits) if hasattr(
        decoder.cell, "get_initial_states") and inits is None else inits
    B = int(np.asarray(unwrap(cell_states[0]) if isinstance(
        cell_states, (list, tuple)) else unwrap(cell_states)).shape[0])
    K = decoder.beam_size

    # expand states beam-wise: [B, ...] -> [B*K, ...]
    def expand(s):
        v = np.asarray(unwrap(s))
        return pt.to_tensor(np.repeat(v, K, axis=0))

    states = [expand(s) for s in cell_states] if isinstance(
        cell_states, (list, tuple)) else expand(cell_states)
    ids = np.full((B * K,), decoder.start_token, np.int64)
    scores = np.full((B, K), -1e9, np.float32)
    scores[:, 0] = 0.0   # only one live hypothesis initially
    finished = np.zeros((B, K), bool)
    lengths = np.zeros((B, K), np.int64)
    history = []

    for _t in range(max_step_num):
        logits, states = decoder._logits(ids, states)
        logp = np.asarray(unwrap(F.log_softmax(logits, axis=-1)))
        V = logp.shape[-1]
        logp = logp.reshape(B, K, V)
        # finished beams only extend with end_token at zero cost
        fin_mask = np.full((V,), -1e9, np.float32)
        fin_mask[decoder.end_token] = 0.0
        logp = np.where(finished[..., None], fin_mask[None, None], logp)
        total = scores[..., None] + logp                    # [B, K, V]
        flat = total.reshape(B, K * V)
        top = np.argsort(-flat, axis=-1)[:, :K]
        scores = np.take_along_axis(flat, top, -1)
        beam_parent = top // V
        tok = top % V
        finished = np.take_along_axis(finished, beam_parent, -1) | (
            tok == decoder.end_token)
        lengths = np.take_along_axis(lengths, beam_parent, -1) + (
            ~finished).astype(np.int64)
        history.append((tok.copy(), beam_parent.copy()))
        # reorder states by beam parent
        gather = (np.arange(B)[:, None] * K + beam_parent).reshape(-1)

        def reorder(s):
            v = np.asarray(unwrap(s))
            return pt.to_tensor(v[gather])

        states = [reorder(s) for s in states] if isinstance(
            states, (list, tuple)) else reorder(states)
        ids = tok.reshape(-1).astype(np.int64)
        if finished.all():
            break

    # backtrace
    T = len(history)
    out = np.zeros((B, K, T), np.int64)
    beam_idx = np.tile(np.arange(K), (B, 1))
    for t in range(T - 1, -1, -1):
        tok, parent = history[t]
        out[:, :, t] = np.take_along_axis(tok, beam_idx, -1)
        beam_idx = np.take_along_axis(parent, beam_idx, -1)
    return pt.to_tensor(out), pt.to_tensor(lengths)
