"""paddle_tpu.nn — layers, functional, initializers, clip."""
from . import functional  # noqa: F401
from . import utils  # noqa: F401
from . import initializer  # noqa: F401
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
from .layer import Layer, LayerDict, LayerList, ParameterList, Sequential  # noqa: F401
from .layers_basic import *  # noqa: F401,F403
from .layers_basic import __all__ as _basic_all
from .rnn import *  # noqa: F401,F403
from .rnn import __all__ as _rnn_all
from .transformer import *  # noqa: F401,F403
from .transformer import __all__ as _transformer_all
from .layers_tail import *  # noqa: F401,F403
from .layers_tail import __all__ as _tail_all

__all__ = (
    ["Layer", "LayerList", "Sequential", "ParameterList", "LayerDict",
     "ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm",
     "functional", "initializer"] + list(_basic_all) + list(_rnn_all)
    + list(_transformer_all) + list(_tail_all)
)
