"""paddle.nn.utils parity (reference python/paddle/nn/utils/):
weight_norm / spectral_norm reparameterizations + parameter vector utils.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor, unwrap, wrap

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters",
           "clip_grad_norm_"]


def _norm_except(w, dim):
    axes = tuple(i for i in range(w.ndim) if i != dim)   # dim=None -> all
    return jnp.sqrt(jnp.sum(jnp.square(w), axis=axes, keepdims=True))


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize ``layer.<name>`` as g * v/||v|| (reference
    weight_norm_hook.py). Recomputed via a forward-pre hook each call so
    optimizing g/v flows into the effective weight."""
    from ..layer import Layer
    assert isinstance(layer, Layer)
    w = getattr(layer, name)
    raw = unwrap(w)
    # dim=None: paddle norms over ALL axes (scalar g); _norm_except
    # handles None naturally via its axis filter
    from ...core.tensor import Parameter
    g = Parameter(_norm_except(raw, dim))
    v = Parameter(raw)
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)
    # the original becomes a derived (non-trainable) buffer value
    w.stop_gradient = True
    if name in layer._parameters:
        del layer._parameters[name]

    def _recompute(lyr, inputs):
        # derived tensor participates in autograd through v/g
        from ...core.tensor import dispatch
        setattr(lyr, name, dispatch(
            lambda vvv, ggg: ggg * vvv / (_norm_except(vvv, dim) + 1e-12),
            getattr(lyr, name + "_v"), getattr(lyr, name + "_g"),
            name="weight_norm"))
        return None

    handle = layer.register_forward_pre_hook(_recompute)
    layer._weight_norm_hook = (handle, name, dim)
    _recompute(layer, None)
    return layer


def remove_weight_norm(layer, name="weight"):
    """Fold g*v/||v|| back into a plain parameter and drop the hook."""
    handle, pname, dim = layer._weight_norm_hook
    if pname != name:
        raise ValueError(f"weight_norm was registered on {pname!r}")
    try:
        handle.remove()
    except AttributeError:
        pass
    vv = unwrap(getattr(layer, name + "_v"))
    gg = unwrap(getattr(layer, name + "_g"))
    eff = gg * vv / (_norm_except(vv, dim) + 1e-12)
    from ...core.tensor import Parameter
    p = Parameter(eff)
    layer.__dict__.pop(name, None)   # drop the derived shadow
    layer.add_parameter(name, p)
    del layer._parameters[name + "_g"]
    del layer._parameters[name + "_v"]
    if hasattr(layer, name + "_g"):
        delattr(layer, name + "_g")
    if hasattr(layer, name + "_v"):
        delattr(layer, name + "_v")
    del layer._weight_norm_hook
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Apply spectral normalization to ``layer.<name>`` via a forward-pre
    hook over the nn.SpectralNorm power-iteration module."""
    from ..layers_basic import SpectralNorm as _SN
    w = getattr(layer, name)
    if dim is None:
        # reference spectral_norm_hook: dim=1 for Linear/Conv*Transpose
        # (their out-features axis is 1), else 0
        from ..layers_basic import (Conv1DTranspose, Conv2DTranspose,
                                    Conv3DTranspose, Linear)
        dim = 1 if isinstance(layer, (Linear, Conv1DTranspose,
                                      Conv2DTranspose,
                                      Conv3DTranspose)) else 0
    sn = _SN(list(w.shape), dim=dim, power_iters=n_power_iterations,
             eps=eps)
    layer.add_sublayer(name + "_spectral_norm", sn)
    orig = layer._parameters.get(name)
    layer.add_parameter(name + "_orig", orig)
    del layer._parameters[name]

    def _recompute(lyr, inputs):
        setattr(lyr, name, sn(getattr(lyr, name + "_orig")))
        return None

    layer.register_forward_pre_hook(_recompute)
    _recompute(layer, None)
    return layer


def parameters_to_vector(parameters, name=None):
    vals = [unwrap(p).reshape(-1) for p in parameters]
    return wrap(jnp.concatenate(vals), stop_gradient=False)


def vector_to_parameters(vec, parameters, name=None):
    v = unwrap(vec)
    off = 0
    for p in parameters:
        n = p.size
        p._replace_value(v[off:off + n].reshape(p._value.shape))
        off += n


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """torch-style in-place grad clip (reference nn/utils/clip_grad.py)."""
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    parameters = list(parameters)   # generators: iterate twice below
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return wrap(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(unwrap(g)))
                                   for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(unwrap(g)) ** norm_type) for g in grads])
        ) ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError("non-finite grad norm")
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad._replace_value(unwrap(p.grad) * scale)
    return wrap(total)
