"""Weight initializers (paddle.nn.initializer parity).

Reference: python/paddle/nn/initializer/ + python/paddle/fluid/initializer.py.
Init happens host-side with the global RNG (core.random), then lands on
device once — no per-init device kernels needed on TPU.
"""
import math

import jax
import jax.numpy as jnp

from ..core import random as rnd
from ..core.dtype import convert_dtype

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "calculate_gain",
]


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4.0,
    }
    return gains[nonlinearity]


def _fan(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = 1
    for s in shape[2:]:
        receptive *= s
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype="float32"):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        return jnp.full(shape, self.value, dtype=convert_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32"):
        d = convert_dtype(dtype)
        return self.mean + self.std * jax.random.normal(rnd.next_key(), shape, d)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32"):
        d = convert_dtype(dtype)
        return self.mean + self.std * jax.random.truncated_normal(
            rnd.next_key(), -2.0, 2.0, shape, d)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype="float32"):
        d = convert_dtype(dtype)
        return jax.random.uniform(rnd.next_key(), shape, d, self.low, self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fi, fo = _fan(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(rnd.next_key(), shape, convert_dtype(dtype))


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fi, fo = _fan(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(rnd.next_key(), shape, convert_dtype(dtype),
                                  -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype="float32"):
        fi, _ = _fan(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return std * jax.random.normal(rnd.next_key(), shape, convert_dtype(dtype))


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype="float32"):
        fi, _ = _fan(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(rnd.next_key(), shape, convert_dtype(dtype),
                                  -limit, limit)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        v = jnp.asarray(getattr(self.value, "_value", self.value),
                        dtype=convert_dtype(dtype))
        assert tuple(v.shape) == tuple(shape), (v.shape, shape)
        return v


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype="float32"):
        d = convert_dtype(dtype)
        rows, cols = shape[0], int(jnp.prod(jnp.array(shape[1:])))
        flat = jax.random.normal(rnd.next_key(), (max(rows, cols), min(rows, cols)),
                                 jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        q = q.T if rows < cols else q
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(d)


class Bilinear(Initializer):
    """Bilinear upsampling kernel init (reference initializer/Bilinear) —
    the standard init for transposed-conv upsample layers."""

    def __call__(self, shape, dtype="float32"):
        import numpy as np
        weight = np.zeros(shape, np.float32)
        if len(shape) != 4:
            raise ValueError("Bilinear expects 4-D weight")
        f = int(np.ceil(shape[3] / 2.0))
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(np.prod(shape)):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            w = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
            weight[(i // (shape[2] * shape[3])) // shape[1],
                   (i // (shape[2] * shape[3])) % shape[1], y, x] = w
        import jax.numpy as jnp
        return jnp.asarray(weight, convert_dtype(dtype) or jnp.float32)


class Dirac(Initializer):
    """Identity-preserving conv init (reference initializer/Dirac)."""

    def __init__(self, groups=1, name=None):
        self._groups = groups

    def __call__(self, shape, dtype="float32"):
        import numpy as np
        w = np.zeros(shape, np.float32)
        out_c, in_c = shape[0], shape[1]
        mid = tuple(s // 2 for s in shape[2:])
        # reference semantics: per group g, delta at (g*opg + d, d)
        opg = out_c // self._groups
        for g in range(self._groups):
            for d in range(min(opg, in_c)):
                w[(g * opg + d, d) + mid] = 1.0
        import jax.numpy as jnp
        return jnp.asarray(w, convert_dtype(dtype) or jnp.float32)


_global_initializer = [None, None]   # (weight_init, bias_init)


def set_global_initializer(weight_init, bias_init=None):
    """Reference set_global_initializer: overrides layer defaults (used by
    Layer.create_parameter when no explicit attr/default is given)."""
    _global_initializer[0] = weight_init
    _global_initializer[1] = bias_init


def get_global_initializer(is_bias=False):
    return _global_initializer[1 if is_bias else 0]


__all__ += ["Bilinear", "Dirac", "set_global_initializer"]
