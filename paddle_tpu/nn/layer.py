"""nn.Layer — module base class.

Reference parity: python/paddle/fluid/dygraph/layers.py (Layer with
parameters/sublayers dicts, forward pre/post hooks, state_dict,
train/eval). TPU-native additions: every Layer doubles as a *functional*
module — ``paddle_tpu.jit.functional_call(layer, params, *args)`` runs
forward with substituted (traced) parameter values, which is how a whole
model becomes one jitted, differentiable step function. Hooks are preserved
because ZeRO-3-style gather-on-use and recompute wrap them (reference:
group_sharded_stage3.py forward hooks).
"""
from __future__ import annotations

import collections
from typing import Iterator

import numpy as np

from ..core.dtype import convert_dtype
from ..core.tensor import Parameter, Tensor
from ..utils.unique_name import generate as unique_name
from . import initializer as I

__all__ = ["Layer", "LayerList", "Sequential", "ParameterList", "LayerDict"]


class HookRemoveHelper:
    _next_id = 0

    def __init__(self, hooks):
        self._hooks = hooks
        self._id = HookRemoveHelper._next_id
        HookRemoveHelper._next_id += 1

    def remove(self):
        self._hooks.pop(self._id, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = convert_dtype(dtype)
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._name_scope = name_scope or self.__class__.__name__.lower()
        self._full_name = unique_name(self._name_scope)

    def full_name(self):
        """Unique per-instance name, e.g. ``linear_0`` (reference
        Layer.full_name, python/paddle/fluid/dygraph/layers.py). Stable
        across deepcopy — the copy keeps the original's name — which is
        what lets by-layer configs (e.g. quantization) survive the
        copy-then-transform flow."""
        return self._full_name

    # ------------------------------------------------------------ attributes
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ first")
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ first")
            layers[name] = value
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params:
                if value is None:
                    del params[name]
                else:
                    raise TypeError(f"cannot assign non-Parameter to param {name}")
            if layers is not None and name in layers and value is None:
                del layers[name]
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        d = self.__dict__
        if "_parameters" in d and name in d["_parameters"]:
            return d["_parameters"][name]
        if "_sub_layers" in d and name in d["_sub_layers"]:
            return d["_sub_layers"][name]
        if "_buffers" in d and name in d["_buffers"]:
            return d["_buffers"][name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in (self._parameters, self._sub_layers, self._buffers):
            if name in store:
                del store[name]
                return
        object.__delattr__(self, name)

    # ------------------------------------------------------------ creation
    def create_parameter(self, shape, dtype=None, default_initializer=None,
                         attr=None, is_bias=False):
        dtype = convert_dtype(dtype) or self._dtype
        init = default_initializer
        # set_global_initializer overrides layer DEFAULTS (reference
        # semantics) but never an explicit attr-specified initializer
        g = I.get_global_initializer(is_bias)
        if g is not None:
            init = g
        if isinstance(attr, I.Initializer):
            # paddle.ParamAttr._to_attr parity: a bare Initializer is a
            # valid weight_attr/bias_attr and wins over the default
            init = attr
        elif attr is not None and getattr(attr, "initializer", None) is not None:
            init = attr.initializer
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierNormal()
        p = Parameter(init(shape, dtype))
        if attr is not None and getattr(attr, "name", None):
            p.name = attr.name
        if attr is not None and getattr(attr, "learning_rate", None) is not None:
            p.optimize_attr["learning_rate"] = attr.learning_rate
        if attr is not None and getattr(attr, "trainable", True) is False:
            p.trainable = False
            p.stop_gradient = True
        return p

    def add_parameter(self, name, parameter):
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # ------------------------------------------------------------ traversal
    def parameters(self, include_sublayers=True) -> list:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True
                         ) -> Iterator[tuple]:
        seen = set()
        for name, layer in (
            self.named_sublayers(prefix=prefix, include_self=True)
            if include_sublayers else [(prefix, self)]
        ):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname, p)

    def sublayers(self, include_self=False) -> list:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False):
        stack = [(prefix, self, True)]
        seen = set()
        out = []
        while stack:
            name, layer, is_root = stack.pop()
            if id(layer) in seen:
                continue
            seen.add(id(layer))
            if not is_root or include_self:
                out.append((name, layer))
            for sname, sub in reversed(layer._sub_layers.items()):
                if sub is None:
                    continue
                stack.append((f"{name}.{sname}" if name else sname, sub, False))
        return out

    def named_buffers(self, prefix="", include_sublayers=True):
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for bname, b in layer._buffers.items():
                if b is None:
                    continue
                yield (f"{name}.{bname}" if name else bname, b)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def children(self):
        return iter(l for l in self._sub_layers.values() if l is not None)

    def named_children(self):
        return iter((n, l) for n, l in self._sub_layers.items() if l is not None)

    def apply(self, fn):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    # ------------------------------------------------------------ mode/dtype
    def train(self):
        for layer in self.sublayers(include_self=True):
            layer.training = True
        return self

    def eval(self):
        for layer in self.sublayers(include_self=True):
            layer.training = False
        return self

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self.astype(dtype)
        return self

    def astype(self, dtype):
        from ..core.dtype import is_floating
        dtype = convert_dtype(dtype)
        for _, p in self.named_parameters():
            if p.dtype != dtype and is_floating(p.dtype):
                p._replace_value(p._value.astype(dtype))
        self._dtype = dtype
        return self

    def float(self):
        return self.astype("float32")

    def half(self):
        return self.astype("float16")

    def bfloat16(self):
        return self.astype("bfloat16")

    # ------------------------------------------------------------ hooks
    def register_forward_pre_hook(self, hook):
        helper = HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[helper._id] = hook
        return helper

    def register_forward_post_hook(self, hook):
        helper = HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[helper._id] = hook
        return helper

    # ------------------------------------------------------------ call
    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = [f"{type(self).__name__}({extra}"]
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).splitlines()
            lines.append(f"  ({name}): " + "\n  ".join(sub_repr))
        lines.append(")")
        return "\n".join(lines) if len(lines) > 2 else f"{type(self).__name__}({extra})"

    # ------------------------------------------------------------ state dict
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix,
                                             include_sublayers=include_sublayers):
            dest[name] = p
        for name, layer in self.named_sublayers(prefix=structured_name_prefix,
                                                include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                dest[f"{name}.{bname}" if name else bname] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            val = v._value if isinstance(v, Tensor) else v
            import jax.numpy as jnp
            own[k]._replace_value(jnp.asarray(val, dtype=own[k].dtype))
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    # ------------------------------------------------------------ functional
    def raw_params(self):
        """name -> jax.Array pytree of trainable params (for jit training)."""
        return {n: p._value for n, p in self.named_parameters() if p.trainable}

    def load_raw_params(self, params):
        named = dict(self.named_parameters())
        for n, v in params.items():
            named[n]._replace_value(v)


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], collections.OrderedDict):
            for name, layer in layers[0].items():
                self.add_sublayer(name, layer)
        else:
            for i, layer in enumerate(layers):
                if isinstance(layer, tuple):
                    self.add_sublayer(layer[0], layer[1])
                else:
                    self.add_sublayer(str(i), layer)

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def append(self, sublayer):
        self.add_sublayer(str(len(self._sub_layers)), sublayer)
        return self

    def insert(self, index, sublayer):
        layers = list(self._sub_layers.values())
        layers.insert(index, sublayer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, sublayers):
        for l in sublayers:
            self.append(l)
        return self

    def __getitem__(self, idx):
        if isinstance(idx, builtin_slice):
            return list(self._sub_layers.values())[idx]
        return self._sub_layers[str(idx if idx >= 0 else len(self) + idx)]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


builtin_slice = slice


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers:
            for k, v in sublayers.items():
                self.add_sublayer(k, v)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def keys(self):
        return self._sub_layers.keys()

    def items(self):
        return self._sub_layers.items()

    def values(self):
        return self._sub_layers.values()

    def update(self, sublayers):
        for k, v in sublayers.items():
            self.add_sublayer(k, v)
