"""Recurrent layers — SimpleRNN/LSTM/GRU cells + RNN/BiRNN wrappers.

Reference parity: python/paddle/nn/layer/rnn.py (RNNCellBase:66,
SimpleRNNCell, LSTMCell, GRUCell, RNN, BiRNN, SimpleRNN/LSTM/GRU via
RNNBase). TPU-native design: the time loop is a single ``lax.scan`` per
layer/direction — one fused XLA while-loop with static shapes, not a
Python per-step loop — so the whole recurrence jits into one program and
the MXU sees batched [B, gates*H] matmuls each step. Gate order matches
the reference (LSTM: i,f,g,o; GRU: r,z,c) so state_dicts interconvert.
"""
import math

import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Parameter, dispatch, unwrap
from . import functional as F
from . import initializer as I
from .layer import Layer

__all__ = [
    "RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell",
    "RNN", "BiRNN", "SimpleRNN", "LSTM", "GRU",
]


def _std_init(hidden_size):
    k = 1.0 / math.sqrt(hidden_size)
    return I.Uniform(-k, k)


class RNNCellBase(Layer):
    """Base for single-step recurrent cells (rnn.py:66)."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        batch = unwrap(batch_ref).shape[batch_dim_idx]
        shape = shape or self.state_shape
        if isinstance(shape, (list, tuple)) and shape and \
                isinstance(shape[0], (list, tuple)):
            return tuple(
                jnp.full((batch,) + tuple(s), init_value,
                         dtype=dtype or jnp.float32) for s in shape)
        return jnp.full((batch,) + tuple(shape), init_value,
                        dtype=dtype or jnp.float32)


class SimpleRNNCell(RNNCellBase):
    """h' = act(x W_ih^T + b_ih + h W_hh^T + b_hh) (rnn.py SimpleRNNCell)."""

    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        if activation not in ("tanh", "relu"):
            raise ValueError("activation must be tanh or relu")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        init = _std_init(hidden_size)
        self.weight_ih = self.create_parameter(
            (hidden_size, input_size), attr=weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            (hidden_size, hidden_size), attr=weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            (hidden_size,), attr=bias_ih_attr, default_initializer=init)
        self.bias_hh = self.create_parameter(
            (hidden_size,), attr=bias_hh_attr, default_initializer=init)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def _weights(self):
        return [self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh]

    def _num_states(self):
        return 1

    def _step(self, w_ih, w_hh, b_ih, b_hh, x, h):
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu
        g = x @ w_ih.T + b_ih + h @ w_hh.T + b_hh
        h2 = act(g)
        return h2, (h2,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        out = dispatch(
            lambda x, h, wi, wh, bi, bh: self._step(wi, wh, bi, bh, x, h)[0],
            inputs, states, *self._weights(), name="simple_rnn_cell")
        return out, out


class LSTMCell(RNNCellBase):
    """Gate order i,f,g,o (rnn.py LSTMCell)."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        init = _std_init(hidden_size)
        self.weight_ih = self.create_parameter(
            (4 * hidden_size, input_size), attr=weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            (4 * hidden_size, hidden_size), attr=weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            (4 * hidden_size,), attr=bias_ih_attr, default_initializer=init)
        self.bias_hh = self.create_parameter(
            (4 * hidden_size,), attr=bias_hh_attr, default_initializer=init)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def _weights(self):
        return [self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh]

    def _num_states(self):
        return 2

    def _step(self, w_ih, w_hh, b_ih, b_hh, x, h, c):
        gates = x @ w_ih.T + b_ih + h @ w_hh.T + b_hh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        c2 = f * c + i * jnp.tanh(g)
        h2 = o * jnp.tanh(c2)
        return h2, (h2, c2)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, c = states
        res = dispatch(
            lambda x, h, c, wi, wh, bi, bh: self._step(wi, wh, bi, bh, x, h, c)[1],
            inputs, h, c, *self._weights(), name="lstm_cell")
        h2, c2 = res
        return h2, (h2, c2)


class GRUCell(RNNCellBase):
    """Gate order r,z,c; h' = z*h + (1-z)*c (rnn.py GRUCell)."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        init = _std_init(hidden_size)
        self.weight_ih = self.create_parameter(
            (3 * hidden_size, input_size), attr=weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            (3 * hidden_size, hidden_size), attr=weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            (3 * hidden_size,), attr=bias_ih_attr, default_initializer=init)
        self.bias_hh = self.create_parameter(
            (3 * hidden_size,), attr=bias_hh_attr, default_initializer=init)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def _weights(self):
        return [self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh]

    def _num_states(self):
        return 1

    def _step(self, w_ih, w_hh, b_ih, b_hh, x, h):
        xg = x @ w_ih.T + b_ih
        hg = h @ w_hh.T + b_hh
        x_r, x_z, x_c = jnp.split(xg, 3, axis=-1)
        h_r, h_z, h_c = jnp.split(hg, 3, axis=-1)
        r = jax.nn.sigmoid(x_r + h_r)
        z = jax.nn.sigmoid(x_z + h_z)
        c = jnp.tanh(x_c + r * h_c)
        h2 = z * h + (1.0 - z) * c
        return h2, (h2,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        out = dispatch(
            lambda x, h, wi, wh, bi, bh: self._step(wi, wh, bi, bh, x, h)[0],
            inputs, states, *self._weights(), name="gru_cell")
        return out, out


def _scan_layer(cell, inputs, init_states, weights, sequence_length=None,
                reverse=False, time_major=False):
    """One lax.scan over time for one cell/direction. Pure-jnp core shared
    by RNN and the stacked SimpleRNN/LSTM/GRU. Positions beyond
    sequence_length keep their last state and emit zero outputs, matching
    the reference's masked update (rnn.py _rnn_dynamic_graph)."""
    n_state = cell._num_states()

    def fn(x, seq_len, *flat):
        states = flat[:n_state]
        ws = flat[n_state:]
        xt = x if time_major else jnp.swapaxes(x, 0, 1)  # [T,B,C]
        T = xt.shape[0]
        steps = jnp.arange(T)
        if reverse:
            xt = jnp.flip(xt, 0)
            steps = jnp.flip(steps, 0)

        def body(carry, inp):
            st = carry
            x_t, t = inp
            out, new_st = cell._step(*ws, x_t, *st)
            if seq_len is not None:
                mask = (t < seq_len)[:, None]  # [B,1]
                new_st = tuple(jnp.where(mask, n, o)
                               for n, o in zip(new_st, st))
                out = jnp.where(mask, out, jnp.zeros_like(out))
            return new_st, out

        final, outs = lax.scan(body, tuple(states), (xt, steps))
        if reverse:
            outs = jnp.flip(outs, 0)
        if not time_major:
            outs = jnp.swapaxes(outs, 0, 1)
        return outs, final

    if sequence_length is None:
        res = dispatch(lambda x, *flat: fn(x, None, *flat),
                       inputs, *init_states, *weights, name="rnn_scan")
    else:
        res = dispatch(lambda x, sl, *flat: fn(x, sl, *flat),
                       inputs, sequence_length, *init_states, *weights,
                       nondiff_args=(1,), name="rnn_scan")
    return res


class RNN(Layer):
    """Wraps a cell into a full-sequence recurrence (rnn.py RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if initial_states is None:
            bdi = 1 if self.time_major else 0
            initial_states = self.cell.get_initial_states(
                inputs, batch_dim_idx=bdi)
        states = initial_states if isinstance(initial_states, (tuple, list)) \
            else (initial_states,)
        outs, final = _scan_layer(
            self.cell, inputs, tuple(states), self.cell._weights(),
            sequence_length=sequence_length, reverse=self.is_reverse,
            time_major=self.time_major)
        final = final if self.cell._num_states() > 1 else final[0]
        return outs, final


class BiRNN(Layer):
    """Forward + backward cells, outputs concatenated (rnn.py BiRNN)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.time_major = time_major
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if initial_states is None:
            st_fw = st_bw = None
        else:
            st_fw, st_bw = initial_states
        out_fw, fin_fw = self.rnn_fw(inputs, st_fw, sequence_length)
        out_bw, fin_bw = self.rnn_bw(inputs, st_bw, sequence_length)
        outs = dispatch(lambda a, b: jnp.concatenate([a, b], axis=-1),
                        out_fw, out_bw, name="concat")
        return outs, (fin_fw, fin_bw)


class _RNNBase(Layer):
    """Stacked multi-layer, optionally bidirectional recurrence
    (rnn.py RNNBase). Parameters live in per-layer cells; weight suffixes
    follow the reference naming for state_dict parity."""

    CELL = None

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 **cell_kwargs):
        super().__init__()
        if direction in ("bidirect", "bidirectional"):
            self.num_directions = 2
        elif direction == "forward":
            self.num_directions = 1
        else:
            raise ValueError(f"bad direction {direction}")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.direction = direction
        self.time_major = time_major
        self.dropout = dropout
        self._cells = []
        for layer_i in range(num_layers):
            in_sz = input_size if layer_i == 0 \
                else hidden_size * self.num_directions
            for d in range(self.num_directions):
                cell = self.CELL(in_sz, hidden_size, **cell_kwargs)
                suffix = f"l{layer_i}" + ("_reverse" if d else "")
                self.add_sublayer(f"cell_{suffix}", cell)
                self._cells.append(cell)

    def _cell_at(self, layer_i, d):
        return self._cells[layer_i * self.num_directions + d]

    def forward(self, inputs, initial_states=None, sequence_length=None):
        n_state = self._cells[0]._num_states()
        L, D = self.num_layers, self.num_directions
        bdi = 1 if self.time_major else 0
        if initial_states is None:
            init_per = [None] * (L * D)
        else:
            # paddle shape: each state [L*D, B, H]
            sts = tuple(initial_states) \
                if isinstance(initial_states, (tuple, list)) \
                else (initial_states,)
            init_per = []
            for i in range(L * D):
                init_per.append(tuple(s[i] for s in sts))
        x = inputs
        finals = []
        for layer_i in range(L):
            outs_dir = []
            for d in range(D):
                cell = self._cell_at(layer_i, d)
                st = init_per[layer_i * D + d]
                if st is None:
                    st = cell.get_initial_states(x, batch_dim_idx=bdi)
                    st = st if isinstance(st, tuple) else (st,)
                elif not isinstance(st, tuple):
                    st = (st,)
                outs, fin = _scan_layer(
                    cell, x, tuple(st), cell._weights(),
                    sequence_length=sequence_length, reverse=bool(d),
                    time_major=self.time_major)
                outs_dir.append(outs)
                finals.append(fin)
            if D == 1:
                x = outs_dir[0]
            else:
                x = dispatch(lambda a, b: jnp.concatenate([a, b], axis=-1),
                             outs_dir[0], outs_dir[1], name="concat")
            if self.dropout > 0.0 and layer_i < L - 1:
                x = F.dropout(x, p=self.dropout, training=self.training)
        # stack finals: list of tuples len L*D → tuple of [L*D, B, H]
        import paddle_tpu as pt
        stacked = tuple(
            pt.stack([f[s] for f in finals], axis=0) for s in range(n_state))
        final_states = stacked if n_state > 1 else stacked[0]
        return x, final_states


class SimpleRNN(_RNNBase):
    CELL = SimpleRNNCell

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kw):
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, activation=activation, **kw)


class LSTM(_RNNBase):
    CELL = LSTMCell


class GRU(_RNNBase):
    CELL = GRUCell
