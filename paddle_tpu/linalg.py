"""paddle.linalg namespace (reference python/paddle/linalg.py): re-exports
the linear-algebra ops from the registry under their linalg names."""
from .ops.registry import OPS as _OPS

__all__ = ["cholesky", "norm", "cond", "cov", "corrcoef", "inv", "eig",
           "eigvals", "multi_dot", "matrix_rank", "svd", "qr", "lu",
           "lu_unpack", "matrix_power", "det", "slogdet", "eigh",
           "eigvalsh", "pinv", "solve", "cholesky_solve",
           "triangular_solve", "lstsq", "cholesky_inverse", "vector_norm",
           "matrix_norm", "householder_product"]

_ALIASES = {"inv": "inverse"}

for _name in __all__:
    globals()[_name] = _OPS[_ALIASES.get(_name, _name)]
del _name
