"""Multi-replica front door: cache-aware routing, failover, rolling
restarts (ROADMAP item 5).

One ``ContinuousBatchingServer`` is a survivable process (PR 3's
supervision, PR 5's prefix cache, PR 6's ragged prefill) — but a single
process per chip group is where "millions of users" actually breaks: a
replica dying loses every queued request it holds, and a fleet without
prefix-aware placement re-prefills the same system prompts on every
replica. ``ReplicaRouter`` is the layer above N replicas that fixes
both:

Routing. Each replica exports a cheap host-side SKETCH of its
radix-tree contents (``PrefixCache.sketch()`` — rolling page-key
fingerprints, no device reads). ``submit()`` routes a prompt to the
replica whose sketch covers its longest page-aligned prefix
(``prefix_fingerprints``) — the same locality insight that motivates
Ragged Paged Attention's page reuse (PAPERS.md), applied one level up:
KV reuse only helps if same-prefix traffic lands on the same pool.
Ties (and sketch misses) fall back to least-loaded by the replicas'
already-exported queue-depth / in-flight / health signals.
``policy="round_robin"`` is the affinity-blind baseline the router
bench compares against.

Robustness. A ``RouterSupervisor`` (per-replica ``CircuitBreaker`` +
``RetryPolicy`` backoff + the shared ``is_serving_state`` verdict)
watches each replica's health: when one goes ``draining`` or ``dead``
its queued requests are harvested via
``ContinuousBatchingServer.evacuate()`` and requeued onto siblings —
bit-exact, because the harvested entries carry their RESOLVED sampling
seeds — while a dead replica's mid-decode slots flush their partial
tokens to waiters exactly as ``stop(drain=False)`` does (mid-decode
work is not replayable without double-streaming). A harvested request
no sibling can take RIGHT NOW (backpressure, every candidate
transiently down) is HELD at the router — the ``router_queue_depth``
backlog, retried every poll — and fails with typed
``ReplicaLostError`` only when the whole fleet is down. Per-replica
circuit breakers divert traffic from a flapping replica after
consecutive dispatch failures, and ``rolling_restart()`` bounces the
fleet one replica at a time with zero failed requests. Request-level
outcomes (deadline expiry, cancellation, a poisoned stream, a
replica's own breaker opening) pass through to the client unchanged —
the router makes replica LOSS transparent, not request failure.

Deadlines hold end to end: ``submit(deadline_s=...)`` fixes an ABSOLUTE
deadline at the router; every (re)dispatch passes the REMAINING budget
to the replica, so time spent queued at the router — or stranded on a
dead replica — is charged against it.

Chaos: ``fault_injector`` arms ``router.dispatch`` (one replica submit
attempt; fires fall through to the next candidate and feed that
replica's breaker) and ``router.evacuate`` (a harvest sweep; fires
abort the sweep — requests stay put and the next supervisor poll
retries).

Everything here is host-side and replica-agnostic: the router only
touches the public server surface (``submit`` / ``wait`` / ``cancel`` /
``evacuate`` / ``health`` / ``queue_depth`` / ``in_flight`` /
``prefix_sketch`` / ``stop`` / ``start``) — which is exactly why a
``remote.RemoteReplica`` (a process-isolated replica behind the typed
wire transport, ISSUE 12) drops in unchanged: the router routes over
any mix of in-process server objects and remote processes, with the
load/affinity reads served from pushed digests instead of in-process
peeks.
"""
import threading
import time

import numpy as np

from ..core.tensor import unwrap
from ..reliability import (CircuitBreaker, DEAD, DEGRADED, DeadlineExceeded,
                           HEALTHY, MigrationError, QueueFullError,
                           ReliabilityError, ReplicaLostError,
                           RequestCancelled, RetryPolicy, ServerClosed,
                           faults, is_serving_state)
from ..telemetry.clock import MonotonicClock
from . import placement as _placement
from .prefix_cache import prefix_fingerprints

__all__ = ["ReplicaRouter", "RouterSupervisor"]


class _RouterRequest:
    """Everything needed to (re)dispatch one request to any replica."""

    __slots__ = ("rid", "ids", "budget", "seed", "on_token", "deadline",
                 "priority", "cancelled", "journey")

    def __init__(self, rid, ids, budget, seed, on_token, deadline,
                 priority=0, journey=None):
        self.rid = rid
        self.ids = ids
        self.budget = budget
        self.seed = seed              # RESOLVED at router submit: a
        self.on_token = on_token      # requeued sibling draws the
        self.deadline = deadline      # identical sampling chain
        self.priority = priority      # preemption class (optimistic
        self.cancelled = False        # admission), travels on requeue
        self.journey = journey        # fleet trace handle ("router"
        #                               hop); rebound per dispatch so
        #                               replica events carry their own
        #                               location label


class _Route:
    """Where a router rid currently lives. ``gen`` bumps on every
    requeue so a ``wait()`` blocked on the OLD replica can tell a stale
    error from a real one."""

    __slots__ = ("idx", "rrid", "gen", "item")

    def __init__(self, idx, rrid, gen, item):
        self.idx = idx
        self.rrid = rrid
        self.gen = gen
        self.item = item


class RouterSupervisor:
    """Health watcher + failover driver for one ``ReplicaRouter``.

    Built from the existing reliability primitives: the shared
    ``is_serving_state`` verdict decides who takes traffic, per-replica
    ``CircuitBreaker``s (owned by the router) divert flapping replicas,
    and a ``RetryPolicy`` backs off the supervisor thread after a
    failed failover sweep (an injected ``router.evacuate`` fault keeps
    the requests ON the replica; a sibling fleet too full to absorb
    the harvest keeps them in the ROUTER's backlog — both retry here).

    ``poll()`` is ONE deterministic sweep — evacuations first, then a
    retry pass over the router-held backlog. Single-threaded tests
    call it directly; ``ReplicaRouter.start()`` runs it on a
    background thread. It never raises: per-replica failover errors
    are counted (``failed_sweeps``, ``last_error``) and retried on the
    next poll.
    """

    def __init__(self, router, retry=None):
        self._router = router
        self.retry = retry if retry is not None else RetryPolicy()
        n = len(router.replicas)
        self.last_states = [None] * n   # last health seen per replica
        self.failed_sweeps = 0
        self.last_error = None

    def poll(self):
        """One watch sweep: evacuate + requeue every non-serving
        replica that still holds work. Returns the number of failover
        attempts that FAILED this sweep (0 = converged)."""
        r = self._router
        errors = 0
        for idx, rep in enumerate(r.replicas):
            state = rep.health
            prev, self.last_states[idx] = self.last_states[idx], state
            if state != prev and r._rec is not None:
                r._rec.record("replica_health", replica=idx,
                              state=state)
                if state == DEAD and prev != DEAD:
                    # a replica just died under the router: capture the
                    # fleet-level postmortem BEFORE the evacuation
                    # sweep tears its queue apart
                    r._capture_postmortem(f"replica {idx} dead",
                                          replica=idx)
            if is_serving_state(state):
                continue
            dead = state == DEAD
            # cheap pre-check so an idle dead/draining replica costs a
            # few lock-free reads per poll, not an evacuation sweep. A
            # dead replica still holding in-flight slots OR parked
            # preempted requests must be swept: both carry partials
            # their waiters are owed (flush_partials covers them)
            if rep.queue_depth() == 0 \
                    and not (dead and (rep.in_flight() > 0
                                       or rep.preempt_pressure() > 0)):
                continue
            try:
                r._failover(idx, flush_partials=dead)
            except Exception as e:    # injected router.evacuate fault:
                errors += 1           # the requests stay put on the
                self.last_error = e   # replica; retry next poll
        r._drain_backlog()            # router-held requests (sibling
        if errors:                    # backpressure) retry every sweep
            self.failed_sweeps += 1
        r._publish_health()
        return errors


class ReplicaRouter:
    """Cache-aware, failure-tolerant front door over N
    ``ContinuousBatchingServer`` replicas.

    >>> reps = [ContinuousBatchingServer(model, cache_backend="paged",
    ...                                  ...) for _ in range(3)]
    >>> router = ReplicaRouter(reps).start()       # starts replicas +
    >>> rid = router.submit(prompt, max_new_tokens=32)   # supervisor
    >>> tokens = router.wait(rid)
    >>> router.rolling_restart()                   # zero failed requests
    >>> router.stop()

    ``policy``: ``"affinity"`` (default — longest cached prefix wins,
    least-loaded fallback), ``"least_loaded"``, or ``"round_robin"``
    (the affinity-blind bench baseline). ``pressure_weight`` (default
    2.0) scales how strongly a replica's ``preempt_pressure()`` counts
    against it in the least-loaded score relative to one queued or
    in-flight request — raise it to divert traffic from a thrashing
    pool sooner, set 0 to ignore preemption pressure entirely.

    ``telemetry`` (``telemetry.RouterTelemetry``, or ``True`` for a
    default one) publishes per-replica routed/affinity/requeue
    counters, the router backlog gauge, and the aggregate
    ``router_health`` gauge; ``serving.serve_metrics(router)`` fronts
    the fleet with one ``/healthz`` (200 iff >= 1 replica is serving).

    ``journeys`` (``telemetry.JourneyRecorder``, or ``True``) turns on
    request-journey tracing: ``submit()`` mints a fleet trace id,
    every hop appends phase events, ``journey(rid)`` returns the
    cross-replica timeline (also ``/debug/journey/<rid>``), and
    ``export_fleet_trace(path)`` writes one merged Perfetto trace with
    flow events connecting a request's hops. ``recorder``
    (``telemetry.FlightRecorder``, or ``True``) records router-level
    events (evacuations, requeues, replica health flips) and captures
    fleet postmortems on replica death; ``postmortems()`` merges them
    with every replica's bundles (``/debug/postmortem``). Disabled
    recorders are treated exactly like None — zero cost.

    ``slos`` (a list of ``telemetry.SLO`` declarations, or a pre-built
    ``SLOEngine``) arms fleet SLO alerting over the MERGED metrics:
    ``fleet_snapshot()`` folds every replica's registry into one
    snapshot (``fleet_metrics()`` renders it as the ``/fleet``
    Prometheus page), and ``slo_report()`` computes multi-window
    rolling burn rates with ok/warning/page alert states — served on
    ``/slo`` and folded into the aggregated ``/healthz`` detail by
    ``serve_metrics(router)``.

    Clocks: deadline math spans router and replicas, so construct the
    replicas with the SAME clock as the router when injecting a
    ``FakeClock`` (real ``MonotonicClock``s already share a time base).

    All traffic must flow through the router: it requeues only requests
    it routed itself (foreign rids found in an evacuated queue are
    dropped back to their own waiters' timeout).
    """

    def __init__(self, replicas, policy="affinity", seed=0,
                 telemetry=None, journeys=None, recorder=None,
                 slos=None, clock=None, fault_injector=None,
                 breakers=None, retry_policy=None, wait_slice=0.05,
                 pressure_weight=2.0, placement=None,
                 disagg_prefill_min_tokens=256,
                 disagg_handoff_at="first_token"):
        if not replicas:
            raise ValueError("ReplicaRouter needs at least one replica")
        if policy not in ("affinity", "least_loaded", "round_robin"):
            raise ValueError(f"policy must be 'affinity', 'least_loaded'"
                             f" or 'round_robin', got {policy!r}")
        if pressure_weight < 0:
            raise ValueError(f"pressure_weight must be >= 0, got "
                             f"{pressure_weight}")
        # disaggregated prefill/decode placement (ISSUE 20): None (the
        # default) keeps the legacy load/affinity routing byte-for-byte;
        # "disaggregated" routes fresh prompts by PHASE — long prompts
        # to prefill specialists (then a pipelined page handoff to a
        # decode replica), short prompts decode-local
        self.placement = _placement.normalize_placement(placement)
        if disagg_handoff_at not in ("first_token", "eager"):
            raise ValueError(
                f"disagg_handoff_at must be 'first_token' (source "
                f"samples token 0, zero re-prefill on the target) or "
                f"'eager' (hand off mid-prefill, target finishes the "
                f"remainder), got {disagg_handoff_at!r}")
        self.disagg_prefill_min_tokens = int(disagg_prefill_min_tokens)
        self.disagg_handoff_at = disagg_handoff_at
        self.replicas = list(replicas)
        self.policy = policy
        self.pressure_weight = float(pressure_weight)
        self._seed = int(seed)
        if telemetry is True:
            from ..telemetry import RouterTelemetry
            telemetry = RouterTelemetry(clock=clock)
        self.telemetry = telemetry
        self._tele = telemetry if (telemetry is not None
                                   and telemetry.enabled) else None
        self._clock = clock if clock is not None else (
            telemetry.clock if self._tele is not None else MonotonicClock())
        # request-journey tracing (telemetry.JourneyRecorder): the
        # router MINTS the fleet trace id at submit and rebinds the
        # handle per dispatch; a disabled recorder is treated exactly
        # like None (requests carry no handle — zero cost)
        if journeys is True:
            from ..telemetry import JourneyRecorder
            journeys = JourneyRecorder(clock=self._clock)
        self.journeys = journeys
        self._jrec = journeys if (journeys is not None
                                  and journeys.enabled) else None
        # flight recorder for ROUTER-level events (evacuations,
        # requeues, replica health flips, fleet postmortems); replicas
        # each carry their own
        if recorder is True:
            from ..telemetry import FlightRecorder
            recorder = FlightRecorder(clock=self._clock)
        self.recorder = recorder
        self._rec = recorder if (recorder is not None
                                 and recorder.enabled) else None
        # fleet SLOs (telemetry.slo): a list of SLO declarations builds
        # an SLOEngine over this router's fleet-merged snapshot (burn
        # metrics ride the router registry when telemetry is on); a
        # pre-built engine is bound to the fleet source if it has none.
        # A disabled engine is treated exactly like None — zero clock
        # reads, zero locks, the source is never called.
        if slos is not None and not hasattr(slos, "evaluate"):
            from ..telemetry.slo import SLOEngine
            slos = SLOEngine(
                slos, self.fleet_snapshot, clock=self._clock,
                registry=self._tele.registry
                if self._tele is not None else None)
        elif slos is not None and slos.source is None:
            slos.bind(self.fleet_snapshot)
        self.slo_engine = slos
        self._slo = slos if (slos is not None
                             and slos.enabled) else None
        self._faults = fault_injector
        if self._faults is not None:
            if self._tele is not None \
                    and hasattr(self._faults, "publish_to"):
                self._faults.publish_to(self._tele.registry)
            if self._rec is not None \
                    and getattr(self._faults, "recorder", None) is None:
                self._faults.recorder = self._rec
        n = len(self.replicas)
        if breakers is None:
            breakers = [CircuitBreaker(failure_threshold=3,
                                       reset_after_s=5.0,
                                       clock=self._clock)
                        for _ in range(n)]
        if len(breakers) != n:
            raise ValueError(f"need one breaker per replica "
                             f"({n}), got {len(breakers)}")
        self._breakers = list(breakers)
        self._wait_slice = float(wait_slice)
        self._lock = threading.RLock()
        self._routes = {}                    # rid -> _Route
        self._by_replica = [dict() for _ in range(n)]   # rrid -> rid
        self._failures = {}                  # rid -> ReliabilityError
        self._backlog = []                   # rids held at the router:
        #   harvested requests no sibling could take YET (backpressure,
        #   or every candidate transiently down) — retried every poll
        self._orphans = {}                   # (idx, rrid) -> ttl: rids
        #   harvested from a replica BEFORE the dispatching thread
        #   could record the route (the replica died inside that gap);
        #   the recorder claims the entry and re-places instead of
        #   routing to a corpse. Unclaimed entries (true foreign
        #   traffic) age out after a few polls.
        self._next_rid = 0
        self._rr = 0                         # round-robin cursor
        self._stats = {"routed": [0] * n, "affinity_hits": 0,
                       "fallbacks": 0, "dispatch_retries": 0,
                       "evacuations": 0, "requeued": 0,
                       "replica_lost": 0, "orphaned": 0, "restarts": 0,
                       # live KV-page migrations: mid-decode requests
                       # handed to a sibling WITH their pages / attempts
                       # degraded to the evacuate+replay path
                       "migrations": 0, "migration_fallbacks": 0,
                       # disaggregated prefill handoffs: prompts a
                       # prefill specialist shipped to a decode replica
                       # / pump attempts degraded to local decode on
                       # the specialist (never a request failure)
                       "handoffs": 0, "handoff_fallbacks": 0}
        self._pumping = set()          # rids with a live handoff pump
        self.supervisor = RouterSupervisor(self, retry=retry_policy)
        self._stop_evt = threading.Event()
        self._thread = None

    # ------------------------------------------------------------ client
    def submit(self, input_ids, max_new_tokens=32, seed=None,
               on_token=None, deadline_s=None, priority=0):
        """Route one prompt to the best replica; returns a ROUTER
        request id (collect with ``wait``). ``deadline_s`` fixes an
        absolute deadline NOW — any time the request later spends
        queued at the router (failover requeue) or on a replica is
        charged against it. ``priority`` is the preemption class
        (replicas running ``admission="optimistic"``); it travels with
        the request across failover requeues. Raises
        ``QueueFullError`` when every serving replica shed it
        (resubmit with backoff) and ``ReplicaLostError`` when no
        replica is serving at all."""
        ids = np.asarray(unwrap(input_ids)).astype(np.int32)
        if ids.ndim == 2:
            if ids.shape[0] != 1:
                raise ValueError("submit() takes one request; batch by "
                                 "calling submit() per row")
            ids = ids[0]
        if deadline_s is not None and deadline_s <= 0:
            raise DeadlineExceeded(
                f"deadline_s={deadline_s} is already expired")
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
            if seed is None:
                # resolve NOW: a replica-assigned default seed would
                # change on requeue and break sampled-token parity
                seed = self._seed + rid
        deadline = None if deadline_s is None \
            else self._clock.now() + float(deadline_s)
        journey = None
        if self._jrec is not None:
            # the fleet trace id: one per ROUTER rid, minted here —
            # every later hop (dispatch, admission, preempt/replay,
            # evacuation, requeue, completion) appends to this timeline
            journey = self._jrec.begin(f"r{rid}", where="router")
            journey.event("submitted", rid=rid,
                          prompt_tokens=int(ids.shape[0]),
                          priority=int(priority))
        item = _RouterRequest(rid, ids, int(max_new_tokens), int(seed),
                              on_token, deadline, int(priority), journey)
        self._place(item, exclude=())
        return rid

    def wait(self, rid, timeout=120.0):
        """Block until ``rid`` finishes ANYWHERE in the fleet; returns
        its new tokens (possibly a partial, if its replica died
        mid-decode). Follows the request across failover requeues;
        typed ``ReliabilityError``s are raised directly."""
        import time as _time
        deadline = _time.monotonic() + timeout
        while True:
            with self._lock:
                if rid in self._failures:
                    self._routes.pop(rid, None)
                    raise self._failures.pop(rid)
                route = self._routes.get(rid)
                if route is None:
                    raise KeyError(f"unknown request id {rid} (never "
                                   f"submitted, or already collected)")
                idx, rrid, gen = route.idx, route.rrid, route.gen
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"request {rid} not finished in {timeout}s")
            try:
                out = self.replicas[idx].wait(
                    rrid, timeout=min(remaining, self._wait_slice))
            except ReliabilityError:
                # matched BEFORE TimeoutError: DeadlineExceeded
                # subclasses both, and it is a terminal typed outcome
                # — the old clause order swallowed it as a
                # not-finished-yet poll and the waiter span until its
                # own timeout, surfacing untyped (ISSUE 12 fix). The
                # stale-gen re-check below still absorbs errors from
                # a replica the request already left.
                with self._lock:
                    cur = self._routes.get(rid)
                    if cur is not None and cur.gen != gen:
                        continue      # requeued mid-wait; stale error
                    if rid in self._failures:
                        self._routes.pop(rid, None)
                        raise self._failures.pop(rid)
                    self._routes.pop(rid, None)
                    self._by_replica[idx].pop(rrid, None)
                raise
            except TimeoutError:
                continue              # re-read the route: it may have
            #                           moved to a sibling meanwhile
            except RuntimeError as e:
                # a DEAD SERVE THREAD raises a generic RuntimeError for
                # every waiter WITHOUT consuming any per-rid state —
                # the request is still queued/in-flight on the corpse
                # and the supervisor's next poll will harvest it; keep
                # waiting instead of leaking a raw thread death to the
                # client. (ReliabilityError subclasses RuntimeError, so
                # typed per-rid failures were already handled above.)
                # Identified by __cause__ IDENTITY with the replica's
                # recorded thread error: a wrapped per-request
                # admission failure also arrives as RuntimeError but
                # DID consume the rid's state — that one must re-raise,
                # even when the thread has also died.
                with self._lock:
                    cur = self._routes.get(rid)
                    if cur is not None and cur.gen != gen:
                        continue
                    if rid in self._failures:
                        self._routes.pop(rid, None)
                        raise self._failures.pop(rid)
                rep = self.replicas[idx]
                if rep._thread_error is not None \
                        and e.__cause__ is rep._thread_error \
                        and not is_serving_state(rep.health):
                    # failover pending; stay blocked (the corpse's
                    # wait() raises instantly, so pace the loop)
                    _time.sleep(min(self._wait_slice, 0.01))
                    continue
                with self._lock:
                    self._routes.pop(rid, None)
                    self._by_replica[idx].pop(rrid, None)
                raise
            else:
                with self._lock:
                    route = self._routes.pop(rid, None)
                    self._by_replica[idx].pop(rrid, None)
                if route is not None and route.item.journey is not None:
                    route.item.journey.event("collected",
                                             tokens=len(out))
                return out

    def cancel(self, rid):
        """Best-effort cancel wherever the request currently lives.
        A request mid-failover (harvested, not yet requeued) is failed
        with ``RequestCancelled`` instead of being requeued."""
        with self._lock:
            route = self._routes.get(rid)
            if route is None:
                return False
            route.item.cancelled = True
            idx, rrid = route.idx, route.rrid
        return self.replicas[idx].cancel(rrid)

    # ----------------------------------------------------------- routing
    def _candidates(self, ids, exclude=(), phase=None):
        """(ordered replica indices to try, {idx: affinity tokens}).
        Serving replicas only (health + closed breaker), best first.
        Under ``placement="disaggregated"`` a ``phase`` rewrites the
        order: prefill work prefers prefill specialists (any serving
        replica as the degradation tail), decode work avoids them
        while anything else serves."""
        if self._tele is not None:
            # gauge from the UNFILTERED health scan (matches .health):
            # a requeue's source exclusion must not read as a capacity
            # dip on dashboards
            self._tele.set_serving(sum(
                1 for rep in self.replicas
                if is_serving_state(rep.health)))
        serving = [idx for idx, rep in enumerate(self.replicas)
                   if idx not in exclude
                   and is_serving_state(rep.health)
                   and self._breakers[idx].would_allow()]
        aff = {idx: 0 for idx in serving}
        if not serving:
            return [], aff
        if self.policy == "round_robin":
            with self._lock:
                k = self._rr % len(serving)
                self._rr += 1
            order = serving[k:] + serving[:k]
            if self.placement is not None and phase is not None:
                order = _placement.order_for_phase(
                    order, self.replicas, phase)
            return order, aff
        # preemption pressure joins the load score, weighted ABOVE
        # plain queue depth (``pressure_weight``, default 2.0): a
        # replica thrashing its KV pool (parked preempted requests it
        # must replay) is slower for EVERY resident request, so the
        # fleet sheds new load away from it until the backlog drains —
        # a higher weight diverts sooner, 0 ignores pressure entirely.
        # Lock-free reads, like the rest.
        w = self.pressure_weight
        load = {idx: (self.replicas[idx].queue_depth()
                      + self.replicas[idx].in_flight()
                      + w * self.replicas[idx].preempt_pressure())
                for idx in serving}
        if self.policy == "affinity":
            fps_by_pg = {}
            for idx in serving:
                pg = self.replicas[idx].page_size
                if not pg:
                    continue          # dense backend: nothing to be
                if pg not in fps_by_pg:                 # affine to
                    fps_by_pg[pg] = prefix_fingerprints(
                        ids, pg, max_tokens=ids.shape[0] - 1)
                sketch = self.replicas[idx].prefix_sketch()
                k = 0
                for fp in fps_by_pg[pg]:
                    if fp not in sketch:
                        break
                    k += 1
                aff[idx] = k * pg
            order = sorted(serving,
                           key=lambda i: (-aff[i], load[i], i))
        else:                         # least_loaded
            order = sorted(serving, key=lambda i: (load[i], i))
        if self.placement is not None and phase is not None:
            order = _placement.order_for_phase(order, self.replicas,
                                               phase)
        return order, aff

    def _dispatch(self, idx, item):
        """One replica submit attempt (the ``router.dispatch`` chaos
        point); returns the REPLICA rid. Charges elapsed time against
        the request's absolute deadline."""
        if item.journey is not None:
            # every ATTEMPT is a journey phase (where="router"): a
            # chaos-failed dispatch shows as this event followed by the
            # next candidate's, so flapping reads straight off the
            # timeline
            item.journey.event("dispatched", replica=idx)
        if self._faults is not None:
            self._faults.check(faults.ROUTER_DISPATCH, rid=item.rid,
                               replica=idx)
        deadline_s = None
        if item.deadline is not None:
            deadline_s = item.deadline - self._clock.now()
            if deadline_s <= 0:
                raise DeadlineExceeded(
                    f"request {item.rid} expired before it could be "
                    f"dispatched to a replica")
        journey = None if item.journey is None \
            else item.journey.at(f"replica{idx}")
        return self.replicas[idx].submit(
            item.ids, max_new_tokens=item.budget, seed=item.seed,
            on_token=item.on_token, deadline_s=deadline_s,
            priority=item.priority, journey=journey)

    def _place(self, item, exclude=()):
        """Dispatch ``item`` to the best willing replica; record the
        route. Raises typed when nobody takes it: ``QueueFullError``
        if every serving replica shed, ``DeadlineExceeded`` if the
        deadline ran out first, else ``ReplicaLostError``."""
        phase = None
        if self.placement is not None:
            phase = _placement.request_phase(
                item.ids, self.disagg_prefill_min_tokens)
        for _rescan in range(4):      # orphan claims force a fresh
            order, aff = self._candidates(item.ids, exclude,    # scan
                                          phase=phase)
            last_err = None
            rescan = False
            for idx in order:
                if not self._breakers[idx].allow():
                    continue   # opened since the candidate scan; the
                try:           # mutating open->half_open probe gate
                    rrid = self._dispatch(idx, item)   # happens HERE
                except DeadlineExceeded:
                    # total expiry: siblings can't help. If allow()
                    # handed us a half-open probe token, return it
                    # UNRESOLVED — the replica was never touched, and
                    # keeping the token would wedge the breaker
                    # half-open with no probe outcome ever recorded
                    self._breakers[idx].release_probe()
                    raise
                except (QueueFullError, ServerClosed) as e:
                    # replica-level shed / drain race: divert, don't
                    # trip the breaker — healthy, just unwilling (and a
                    # shed is no probe VERDICT either: hand a half-open
                    # token back so another attempt may probe)
                    self._breakers[idx].release_probe()
                    last_err = e
                    self._note_retry(idx)
                    continue
                except Exception as e:
                    # dispatch fault / unexpected submit error: this is
                    # what "flapping" looks like from the router — feed
                    # the replica's breaker
                    last_err = e
                    self._breakers[idx].record_failure()
                    self._note_retry(idx)
                    continue
                self._breakers[idx].record_success()
                hit = aff.get(idx, 0) > 0
                with self._lock:
                    if self._orphans.pop((idx, rrid), None) is not None:
                        # the replica accepted this request and died —
                        # and the supervisor already harvested it —
                        # before we could record the route. The request
                        # exists NOWHERE now; recording would point a
                        # waiter at a corpse forever. Start over with a
                        # FRESH candidate scan (the fleet just changed
                        # under us — the stale tail of this order is
                        # not the full picture).
                        rescan = True
                    else:
                        prev = self._routes.get(item.rid)
                        gen = 0 if prev is None else prev.gen + 1
                        self._routes[item.rid] = _Route(idx, rrid, gen,
                                                        item)
                        self._by_replica[idx][rrid] = item.rid
                        self._stats["routed"][idx] += 1
                        if hit:
                            self._stats["affinity_hits"] += 1
                        else:
                            self._stats["fallbacks"] += 1
                if rescan:
                    break
                if self._tele is not None:
                    self._tele.on_routed(idx, hit)
                if (phase == "prefill" and not item.cancelled
                        and _placement.replica_role(
                            self.replicas[idx]) == "prefill"):
                    # a long prompt landed on a prefill specialist:
                    # start the pipelined handoff pump that streams
                    # its pages to a decode sibling as chunks complete
                    self._spawn_handoff(item.rid, idx)
                return idx
            if rescan:
                continue              # re-scan (bounded: each retry
            break                     # needs ANOTHER mid-gap death)
        if isinstance(last_err, QueueFullError):
            raise last_err            # backpressure, not loss: resubmit
        err = ReplicaLostError(
            f"request {item.rid}: no serving replica could take it "
            f"({len(self.replicas)} replicas total)")
        err.__cause__ = last_err
        raise err

    def _note_retry(self, idx):
        with self._lock:
            self._stats["dispatch_retries"] += 1
        if self._tele is not None:
            self._tele.on_dispatch_retry(idx)

    # ----------------------------------------------------- live migration
    def _migrate_live(self, idx):
        """Hand replica ``idx``'s mid-decode requests to siblings WITH
        their KV pages (ISSUE 18): each migrated request resumes
        exactly where it paused — zero re-prefill, zero token replay,
        zero partial flush. Best-effort per request: any failure (not
        migratable, page frames lost to the wire, no sibling with
        capacity, target refusal) leaves the request decoding on the
        source for the legacy drain/evacuate path and counts a
        fallback — never a request failure. Returns the number
        migrated."""
        rep = self.replicas[idx]
        if not (hasattr(rep, "migrate_out")
                and hasattr(rep, "migrate_in")):
            return 0
        with self._lock:
            pairs = list(self._by_replica[idx].items())  # rrid -> rid
        moved = 0
        for rrid, rid in pairs:
            with self._lock:
                route = self._routes.get(rid)
            if route is None or route.idx != idx \
                    or route.item.cancelled:
                continue
            item = route.item
            try:
                state, payloads = rep.migrate_out(rrid)
            except MigrationError:
                continue    # not mid-decode here (queued, finishing):
                #             nothing to migrate — evacuate covers it
            except Exception:
                continue    # wire down / injected gather fault: the
                #             slot was never paused (or already
                #             resumed); the drain path takes over
            new_rrid = None
            tdx = None
            order, _ = self._candidates(item.ids, exclude=(idx,),
                                        phase="decode")
            for cand in order:
                target = self.replicas[cand]
                if not hasattr(target, "migrate_in"):
                    continue
                journey = None if item.journey is None \
                    else item.journey.at(f"replica{cand}")
                try:
                    new_rrid = target.migrate_in(
                        state, payloads, on_token=item.on_token,
                        journey=journey)
                except Exception:
                    continue    # OutOfPages / restore fault / refusal:
                    #             try the next sibling
                tdx = cand
                break
            if new_rrid is None:
                rep.migrate_abort(rrid)   # resume decoding at home
                with self._lock:
                    self._stats["migration_fallbacks"] += 1
                if item.journey is not None:
                    item.journey.event("migrating", at="router",
                                       source=idx, fallback=True)
                continue
            # COMMIT: the request lives on the target now. Re-home the
            # route FIRST (a waiter blocked on the source re-reads it
            # within one wait slice; the gen bump marks stale errors),
            # THEN release the source slot — so no window exists where
            # a waiter can race a released rid.
            with self._lock:
                self._by_replica[idx].pop(rrid, None)
                cur = self._routes.get(rid)
                if cur is route:
                    route.idx, route.rrid = tdx, new_rrid
                    route.gen += 1
                self._by_replica[tdx][new_rrid] = rid
                self._stats["migrations"] += 1
            if item.journey is not None:
                item.journey.event("migrating", at="router",
                                   source=idx, target=tdx)
            if self._rec is not None:
                self._rec.record("migration", rid=rid, source=idx,
                                 target=tdx)
            rep.migrate_finish(rrid)
            moved += 1
        return moved

    # ------------------------------------------------ prefill->decode handoff
    def _spawn_handoff(self, rid, idx):
        """Start the pipelined handoff pump for router request ``rid``
        placed on prefill specialist ``idx`` (at most one pump per
        rid)."""
        with self._lock:
            if rid in self._pumping:
                return
            self._pumping.add(rid)
        threading.Thread(target=self._run_handoff, args=(rid, idx),
                         daemon=True, name=f"handoff-r{rid}").start()

    def _open_staging(self, item, frag, src_idx):
        """Pick a decode-handoff target (prefix affinity, then pool
        headroom — ``placement.order_handoff_targets``) and open a
        staged restore on it. Returns ``(tdx, target, handle)`` or
        ``None`` when no sibling can stage right now (the pump falls
        back to the one-shot path, or the request just stays put)."""
        begin_state = {
            "rid": int(item.rid), "ids": np.asarray(item.ids),
            "prompt_len": int(np.asarray(item.ids).shape[0]),
            "budget": int(item.budget), "seed": item.seed,
            "page_size": int(frag["page_size"]), "phase": "prefill",
        }
        order, aff = self._candidates(item.ids, exclude=(src_idx,),
                                      phase="decode")
        order = _placement.order_handoff_targets(order, self.replicas,
                                                 aff)
        for cand in order:
            target = self.replicas[cand]
            if not hasattr(target, "migrate_in_begin"):
                continue
            try:
                handle = target.migrate_in_begin(begin_state)
            except Exception:
                continue    # OutOfPages / role refusal / wire down:
            return cand, target, handle   # the next candidate may stage
        return None

    def _run_handoff(self, rid, src_idx):
        """One pipelined prefill->decode handoff (the tentpole's
        pipelining): poll ``migrate_out(partial=True)`` on the prefill
        specialist and stream each completed chunk's pages to a staged
        decode target while later chunks are still prefilling; when the
        source reaches the cut point (first token sampled for
        ``disagg_handoff_at="first_token"``, first shipped batch for
        ``"eager"``) pull the closing state + unshipped tail pages with
        ``migrate_out(from_page=k)`` and commit. Best-effort
        throughout: any failure aborts the target staging and leaves
        the request running on the specialist (it still decodes
        locally — degraded, never lost), counted as a
        ``handoff_fallback``."""
        rep = self.replicas[src_idx]
        t0 = self._tele.handoff_started() if self._tele is not None \
            else None
        tdx = target = handle = None
        delivered = set()   # absolute page indices confirmed on target
        attempted = False   # staged or paused: a failure is a FALLBACK
        try:
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                with self._lock:
                    route = self._routes.get(rid)
                if route is None or route.idx != src_idx \
                        or route.item.cancelled:
                    return          # finished / evacuated / cancelled:
                item = route.item   # nothing to hand off (not a
                rrid = route.rrid   # fallback — the request is fine)
                try:
                    frag, payloads = rep.migrate_out(rrid, partial=True)
                except MigrationError:
                    time.sleep(0.002)   # queued, not admitted yet, or
                    continue            # mid-activation: poll again
                except Exception:
                    break               # wire down: fall back
                if str(frag.get("phase")) != "prefill":
                    break   # first token sampled at the source — cut
                if payloads:
                    if handle is None:
                        staged = self._open_staging(item, frag, src_idx)
                        if staged is None:
                            break   # nobody can stage: one-shot below
                        tdx, target, handle = staged
                        attempted = True
                    if not self._pump_frames(target, handle, frag,
                                             payloads, delivered):
                        # target rejected frames (sha, staging died):
                        # drop it and retry one-shot on the tail pull
                        try:
                            target.migrate_in_abort(handle)
                        except Exception:
                            pass
                        tdx = target = handle = None
                        delivered.clear()
                        break
                    if self.disagg_handoff_at == "eager":
                        break   # hand off mid-prefill: the target
                        #         finishes the remaining chunks
                time.sleep(0.002)
            else:
                if attempted:   # timed out mid-pump: pages staged but
                    self._handoff_fallback(rid, src_idx, t0)   # no cut
                return
            # closing pull: k = pages the target PROVABLY holds as a
            # contiguous prefix; everything >= k rides the tail frames
            k = 0
            while k in delivered:
                k += 1
            for _attempt in range(3):
                with self._lock:
                    route = self._routes.get(rid)
                if route is None or route.idx != src_idx \
                        or route.item.cancelled:
                    return
                item, rrid = route.item, route.rrid
                try:
                    state, tail = rep.migrate_out(rrid, from_page=k)
                except MigrationError:
                    return      # finished / replaced at the source
                except Exception:
                    break
                attempted = True
                if any(p is None for p in tail):
                    rep.migrate_abort(rrid)   # chaos ate tail frames:
                    continue                  # resume, re-pull
                journey = None
                new_rrid = None
                try:
                    if handle is not None:
                        journey = None if item.journey is None else \
                            item.journey.at(f"replica{tdx}")
                        new_rrid = target.migrate_in_commit(
                            handle, state, tail,
                            on_token=item.on_token, journey=journey)
                    else:
                        # nothing was pipelined (short prefill beat the
                        # pump, or no stage-capable sibling): one-shot
                        # handoff through the classic migrate_in
                        staged = self._candidates(
                            item.ids, exclude=(src_idx,),
                            phase="decode")
                        order = _placement.order_handoff_targets(
                            staged[0], self.replicas, staged[1])
                        for cand in order:
                            tgt = self.replicas[cand]
                            if not hasattr(tgt, "migrate_in"):
                                continue
                            journey = None if item.journey is None \
                                else item.journey.at(f"replica{cand}")
                            try:
                                new_rrid = tgt.migrate_in(
                                    state, tail,
                                    on_token=item.on_token,
                                    journey=journey)
                            except Exception:
                                continue
                            tdx, target = cand, tgt
                            break
                        if new_rrid is None:
                            rep.migrate_abort(rrid)
                            break
                except MigrationError:
                    rep.migrate_abort(rrid)   # staging drift / missing
                    continue                  # pages: resume, re-pull
                except Exception:
                    rep.migrate_abort(rrid)
                    break
                if new_rrid is None:
                    continue
                handle = None   # committed: nothing left to abort
                # COMMIT - mirrors _migrate_live: re-home the route
                # FIRST so a waiter never races a released source slot
                with self._lock:
                    self._by_replica[src_idx].pop(rrid, None)
                    cur = self._routes.get(rid)
                    if cur is route:
                        route.idx, route.rrid = tdx, new_rrid
                        route.gen += 1
                    self._by_replica[tdx][new_rrid] = rid
                    self._stats["handoffs"] += 1
                if item.journey is not None:
                    item.journey.event("handoff", at="router",
                                       source=src_idx, target=tdx)
                if self._rec is not None:
                    self._rec.record("handoff", rid=rid,
                                     source=src_idx, target=tdx,
                                     pipelined_pages=len(delivered))
                rep.migrate_finish(rrid)
                if self._tele is not None:
                    self._tele.on_handoff("ok", t0)
                return
            # fall through: every closing attempt failed
            if attempted:
                self._handoff_fallback(rid, src_idx, t0)
        finally:
            if handle is not None:      # staging still open: release
                try:                    # the target's placeholder pages
                    target.migrate_in_abort(handle)
                except Exception:
                    pass
            self._pumping.discard(rid)

    def _pump_frames(self, target, handle, frag, payloads, delivered):
        """Forward one partial batch's page frames to the staged
        target, skipping wire-lost holes (``None`` payloads — the
        closing pull re-ships them). Updates ``delivered`` with the
        ABSOLUTE page indices the target acknowledged. False when the
        target refuses the staging (caller drops it)."""
        base0 = int(frag.get("base") or 0)
        shas = frag.get("sha256") or [None] * len(payloads)
        i = 0
        while i < len(payloads):
            if payloads[i] is None:
                i += 1
                continue
            j = i
            while j < len(payloads) and payloads[j] is not None:
                j += 1
            try:
                got = target.migrate_in_pages(
                    handle, base0 + i, payloads[i:j], shas[i:j])
            except Exception:
                return False
            if isinstance(got, int):    # in-process server: a count
                delivered.update(range(base0 + i, base0 + i + got))
            else:                       # remote client: absolute
                delivered.update(int(p) for p in got)   # landed pages
            i = j
        return True

    def _handoff_fallback(self, rid, src_idx, t0):
        with self._lock:
            self._stats["handoff_fallbacks"] += 1
            route = self._routes.get(rid)
        if route is not None and route.item.journey is not None:
            route.item.journey.event("handoff", at="router",
                                     source=src_idx, fallback=True)
        if self._tele is not None:
            self._tele.on_handoff("fallback", t0)

    # ---------------------------------------------------------- failover
    def _failover(self, idx, flush_partials):
        """Harvest replica ``idx``'s queue (the ``router.evacuate``
        chaos point — an injected fault aborts BEFORE any state moves)
        and requeue everything onto siblings. A draining (not dead)
        replica's mid-decode slots are live-migrated first — pages and
        sampler state hand off to a sibling instead of riding out the
        drain on a sick replica; a dead one has no wire to pull pages
        over, so its mirror-synthesized partial flush stands."""
        if self._faults is not None:
            self._faults.check(faults.ROUTER_EVACUATE, replica=idx)
        if not flush_partials:
            self._migrate_live(idx)
        harvested = self.replicas[idx].evacuate(
            flush_partials=flush_partials)
        with self._lock:
            self._stats["evacuations"] += 1
        if self._tele is not None:
            self._tele.on_evacuation(idx)
        if self._rec is not None:
            self._rec.record("evacuation", replica=idx,
                             harvested=len(harvested),
                             flush_partials=bool(flush_partials))
        self._requeue(idx, harvested)

    def _requeue(self, src, harvested):
        """Re-place harvested requests on siblings, oldest first. A
        request nobody can take RIGHT NOW is held at the router (the
        ``router_queue_depth`` backlog, retried every poll) as long as
        the condition looks transient — sibling backpressure, or every
        candidate momentarily down; it fails typed only when the whole
        fleet is dead (``ReplicaLostError``), its deadline ran out
        while stranded (``DeadlineExceeded``), or it was cancelled."""
        for pending in harvested:
            with self._lock:
                rid = self._by_replica[src].pop(pending.rid, None)
                route = self._routes.get(rid) if rid is not None else None
                if route is None:
                    # either true foreign traffic, or a router dispatch
                    # whose route is not recorded YET (the replica died
                    # between accepting the submit and the dispatching
                    # thread re-taking the router lock): park it so the
                    # recorder can claim-and-replace instead of routing
                    # the waiter to a corpse
                    self._orphans[(src, pending.rid)] = 3   # polls to live
                    continue
            if route.item.journey is not None:
                route.item.journey.event("evacuated", source=src)
            self._try_place(rid, route.item, exclude=(src,))
        self._publish_backlog()

    def _try_place(self, rid, item, exclude=()):
        """One requeue attempt for a router-held request; places it,
        holds it in the backlog, or fails it typed (see ``_requeue``)."""
        if item.cancelled:
            self._record_failure(rid, RequestCancelled(
                f"request {rid} cancelled during failover"))
            return
        if item.deadline is not None \
                and self._clock.now() >= item.deadline:
            self._record_failure(rid, DeadlineExceeded(
                f"request {rid} expired while awaiting requeue"))
            return
        try:
            dst = self._place(item, exclude=exclude)
        except (DeadlineExceeded, RequestCancelled) as e:
            self._record_failure(rid, e)
        except QueueFullError:
            # sibling backpressure is TRANSIENT: hold the request at
            # the router and retry next poll — failing it here would
            # turn a seconds-long full queue into a lost request
            with self._lock:
                self._backlog.append(rid)
            if item.journey is not None:
                item.journey.event("held", why="backpressure")
        except ReliabilityError as e:
            if any(is_serving_state(rep.health)
                   for rep in self.replicas):
                # someone is alive but could not take it this sweep
                # (excluded source, drain race, injected dispatch
                # faults on every candidate): transient — hold it
                with self._lock:
                    self._backlog.append(rid)
                if item.journey is not None:
                    item.journey.event("held", why="no_candidate")
                return
            err = e if isinstance(e, ReplicaLostError) else \
                ReplicaLostError(
                    f"request {rid}: its replica was lost and no "
                    f"sibling could take the requeue")
            if err is not e:
                err.__cause__ = e
            with self._lock:
                self._stats["replica_lost"] += 1
            if self._tele is not None:
                self._tele.on_replica_lost()
            if self._rec is not None:
                self._rec.record("replica_lost", rid=rid)
                # the whole fleet is down and a request just died with
                # it: freeze the routing state for the incident review
                self._capture_postmortem("replica_lost", rid=rid)
            self._record_failure(rid, err)
        else:
            with self._lock:
                self._stats["requeued"] += 1
            if self._tele is not None:
                self._tele.on_requeued(dst)
            if self._rec is not None:
                self._rec.record("requeued", rid=rid, replica=dst)

    def _drain_backlog(self):
        """Retry every router-held request (called once per supervisor
        poll). No source exclusion here: a restarted replica may take
        its old work back. Orphan entries that aged out without a
        route claiming them are TRUE FOREIGN traffic (submitted
        straight to the replica, not through this router): their
        waiters block on the source replica, so fail them THERE, typed
        and promptly, instead of letting them run out their own
        timeouts (the PR-7 known cut this closes)."""
        with self._lock:
            backlog, self._backlog = self._backlog, []
            expired = [k for k, ttl in self._orphans.items() if ttl <= 1]
            self._orphans = {k: ttl - 1
                             for k, ttl in self._orphans.items()
                             if ttl > 1}
        for src, rrid in expired:
            err = ReplicaLostError(
                f"request {rrid} was evacuated off replica {src} but "
                f"belongs to no route of this router (foreign traffic "
                f"submitted directly to the replica?) — it cannot be "
                f"requeued, submit through the router instead")
            if self.replicas[src].abandon(rrid, err):
                with self._lock:
                    self._stats["orphaned"] += 1
                if self._tele is not None:
                    self._tele.on_orphaned()
        for rid in backlog:
            with self._lock:
                route = self._routes.get(rid)
            if route is None:
                continue              # settled/cancelled meanwhile
            self._try_place(rid, route.item)
        self._publish_backlog()

    def _publish_backlog(self):
        if self._tele is not None:
            with self._lock:
                n = len(self._backlog)
            self._tele.set_backlog(n)

    @property
    def backlog(self):
        """Requests currently held at the router awaiting a sibling
        that can take them (the ``router_queue_depth`` gauge)."""
        with self._lock:
            return len(self._backlog)

    def _record_failure(self, rid, err):
        # wait() notices within one poll slice; no condition variable
        # needed (waiters block on the REPLICA's cv, not the router's)
        with self._lock:
            route = self._routes.pop(rid, None)
            self._failures[rid] = err
        if route is not None and route.item.journey is not None:
            route.item.journey.event("failed",
                                     error=type(err).__name__)

    # ------------------------------------------------------ fleet metrics
    def fleet_snapshot(self):
        """ONE fleet-wide registry snapshot: the router's own metrics
        (when telemetry is on) merged with every replica's —
        counters/gauges summed, histograms folded bucket-wise
        (``telemetry.exposition.merge_snapshots``). Replicas without
        telemetry contribute nothing. This is also the SLO engine's
        default source."""
        from ..telemetry.exposition import merge_snapshots
        snaps = []
        if self._tele is not None:
            snaps.append(self._tele.registry.snapshot())
        for rep in self.replicas:
            tele = getattr(rep, "telemetry", None)
            if tele is not None and getattr(tele, "enabled", False):
                snaps.append(tele.registry.snapshot())
                continue
            # process-isolated replica (RemoteReplica): its registry
            # lives across the wire — one snapshot op per fleet fold,
            # so /fleet spans process boundaries. Only serving replicas
            # are asked (a stale/dead one would spend the scrape's wire
            # budget to contribute nothing); the snapshot op itself is
            # bounded by the proxy's short snapshot timeout
            remote = getattr(rep, "registry_snapshot", None)
            if callable(remote) and is_serving_state(rep.health):
                snap = remote()
                if snap:
                    snaps.append(snap)
        return merge_snapshots(snaps)

    def fleet_metrics(self):
        """The merged fleet snapshot as ONE Prometheus text page —
        served on ``/fleet`` by ``serve_metrics(router)``, and
        round-trippable through ``telemetry.parse_prometheus`` (parsed
        values equal the element-wise sum of the per-replica pages)."""
        from ..telemetry.exposition import render_snapshot
        return render_snapshot(self.fleet_snapshot())

    def slo_report(self):
        """Evaluate the fleet SLOs NOW (one clock read, one merged
        snapshot) and return the burn-rate report — ``/slo``'s payload
        and the ``/healthz`` ``"slo"`` detail. None without an enabled
        ``SLOEngine``."""
        if self._slo is None:
            return None
        return self._slo.evaluate()

    # ----------------------------------------------- journeys/postmortem
    def journey(self, rid):
        """The fleet-wide timeline for router request ``rid`` — every
        hop's phase events (submitted, dispatched, queued, admitted,
        prefill chunks, grow/preempted/replay, evacuated, requeued,
        finished/failed/collected) in arrival order, each stamped with
        ``where`` ("router" / "replicaN"). None without a journey
        recorder or for an unknown/evicted rid. Served over
        ``/debug/journey/<rid>`` by ``serve_metrics(router)``."""
        if self._jrec is None:
            return None
        return self._jrec.journey(f"r{int(rid)}")

    def _capture_postmortem(self, reason, **extra):
        """Freeze the router's view of the fleet into a postmortem
        bundle: routing table, backlog, orphan count, per-replica
        breaker + health/load snapshots, router stats — alongside the
        recorder's recent events."""
        if self._rec is None:
            return None
        with self._lock:
            routing = {
                "routes": {rid: {"replica": rt.idx, "rrid": rt.rrid,
                                 "gen": rt.gen}
                           for rid, rt in self._routes.items()},
                "backlog": list(self._backlog),
                "orphans": len(self._orphans),
                "stats": {**self._stats,
                          "routed": list(self._stats["routed"])},
            }
        return self._rec.postmortem(
            reason, routing=routing,
            breakers=[b.state for b in self._breakers],
            replicas=[{"health": rep.health,
                       "queue_depth": rep.queue_depth(),
                       "in_flight": rep.in_flight(),
                       "preempt_pressure": rep.preempt_pressure()}
                      for rep in self.replicas],
            **extra)

    def postmortems(self):
        """Every captured bundle across the fleet, oldest first: the
        router's own (tagged ``source="router"``) merged with each
        replica's (``source="replicaN"``) — one artifact stream for
        ``/debug/postmortem``."""
        out = []
        if self._rec is not None:
            for b in self._rec.postmortems():
                out.append({"source": "router", **b})
        for idx, rep in enumerate(self.replicas):
            for b in rep.postmortems():
                out.append({"source": f"replica{idx}", **b})
        out.sort(key=lambda b: b.get("t", 0.0))
        return out

    def export_fleet_trace(self, file):
        """Write ONE merged Chrome/Perfetto trace for the whole fleet:
        each replica's tracer spans on its own pid (pid 0 = router,
        pid i+1 = replica i), every journey's phase events as instant
        markers at the pid of the hop that emitted them, and flow
        events (``ph: s/t/f``, one shared id per journey) connecting a
        request's hops — a failover renders as a connected arrow from
        the dead replica through the router to the sibling. ``file``
        is a path or file object; returns the event count."""
        import json

        events = [{"ph": "M", "name": "process_name", "pid": 0,
                   "tid": 0, "args": {"name": "router"}}]
        for idx, rep in enumerate(self.replicas):
            events.append({"ph": "M", "name": "process_name",
                           "pid": idx + 1, "tid": 0,
                           "args": {"name": f"replica{idx}"}})
            tele = getattr(rep, "telemetry", None)
            if tele is not None and getattr(tele, "enabled", False):
                for ev in tele.tracer.events():
                    ev = dict(ev)
                    ev["pid"] = idx + 1
                    events.append(ev)

        def pid_of(where):
            if isinstance(where, str) and where.startswith("replica"):
                return int(where[len("replica"):]) + 1
            return 0

        if self._jrec is not None:
            for tid in self._jrec.ids():
                timeline = self._jrec.journey(tid) or []
                for ev in timeline:
                    args = {k: v for k, v in ev.items()
                            if k not in ("t", "phase", "where")}
                    args["journey"] = tid
                    events.append({"name": f"journey.{ev['phase']}",
                                   "ph": "i", "s": "p",
                                   "pid": pid_of(ev["where"]), "tid": 0,
                                   "ts": ev["t"] * 1e6, "args": args})
                # one flow per journey, one step bound to EVERY journey
                # event (not one per consecutive-`where` group): each
                # s/t/f step carries the exact timestamp and pid of the
                # event it binds to, so an A->B->A bounce renders as
                # two distinct arrows anchored at the events that
                # crossed the boundary — and interleaved timelines
                # (replica events landing between two router events)
                # cannot collapse or fabricate hops. Journeys that
                # never left one location draw no flow.
                if len(timeline) >= 2 \
                        and len({ev["where"] for ev in timeline}) >= 2:
                    for i, ev in enumerate(timeline):
                        ph = "s" if i == 0 else \
                            ("f" if i == len(timeline) - 1 else "t")
                        fe = {"name": "journey", "cat": "journey",
                              "ph": ph, "id": tid,
                              "pid": pid_of(ev["where"]), "tid": 0,
                              "ts": ev["t"] * 1e6}
                        if ph == "f":
                            fe["bt"] = "e"
                        events.append(fe)
        payload = {"traceEvents": events, "displayTimeUnit": "ms"}
        if hasattr(file, "write"):
            json.dump(payload, file)
        else:
            with open(file, "w") as f:
                json.dump(payload, f)
        return len(events)

    # ------------------------------------------------------------ health
    @property
    def health(self):
        """Aggregate fleet health: ``healthy`` (all replicas serving),
        ``degraded`` (some down, still taking traffic), ``dead`` (none
        serving). ``/healthz`` via ``serve_metrics(router)`` answers
        200 iff this is a serving state — i.e. >= 1 replica up."""
        n_serving = sum(1 for rep in self.replicas
                        if is_serving_state(rep.health))
        if n_serving == len(self.replicas):
            return HEALTHY
        return DEGRADED if n_serving else DEAD

    def _publish_health(self):
        if self._tele is not None:
            self._tele.set_health(self.health)
            for idx, rep in enumerate(self.replicas):
                # role rides the same publish cadence as health: a
                # restarted host that comes back with a different role
                # (or a pre-role build, -> "hybrid") updates within one
                # supervisor poll
                self._tele.set_replica_role(
                    idx, _placement.replica_role(rep))

    @property
    def stats(self):
        """Copy of the router counters: per-replica ``routed``,
        ``affinity_hits`` / ``fallbacks``, ``dispatch_retries``,
        ``evacuations`` / ``requeued`` / ``replica_lost``,
        ``restarts``."""
        with self._lock:
            out = dict(self._stats)
            out["routed"] = list(out["routed"])
            return out

    @property
    def failures(self):
        """{rid: exception} for requests the router itself failed
        (``wait(rid)`` pops and raises each)."""
        with self._lock:
            return dict(self._failures)

    def poll(self):
        """One supervisor sweep (see ``RouterSupervisor.poll``) —
        single-threaded/deterministic drives call this instead of
        ``start()``."""
        return self.supervisor.poll()

    # --------------------------------------------------------- lifecycle
    def start(self, poll_interval=0.01, start_replicas=True):
        """Start the supervisor thread (and, by default, any replica
        serve thread not already running). The supervisor polls health
        every ``poll_interval`` seconds, backing off by the retry
        policy after a failed failover sweep."""
        if self._thread is not None:
            raise RuntimeError("router already started")
        if start_replicas:
            for rep in self.replicas:
                if rep._thread is None:
                    rep.start()
        self._stop_evt.clear()

        def loop():
            attempt = 0
            delay = poll_interval
            while not self._stop_evt.wait(delay):
                errors = self.supervisor.poll()
                if errors:
                    delay = poll_interval \
                        + self.supervisor.retry.delay(attempt)
                    attempt += 1
                else:
                    delay = poll_interval
                    attempt = 0

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self, drain=True, timeout=60.0, stop_replicas=True):
        """Stop the supervisor thread, then (by default) every replica
        — gracefully with ``drain=True``."""
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        if stop_replicas:
            for rep in self.replicas:
                rep.stop(timeout=timeout, drain=drain)
        self._publish_health()

    def rolling_restart(self, drain_timeout=120.0):
        """Bounce every replica one at a time with ZERO failed
        requests: its queued work is evacuated to siblings first (they
        also absorb all new traffic once health goes ``draining``),
        in-flight requests finish during the graceful drain, then the
        replica restarts and rejoins the rotation before the next one
        goes down."""
        for idx, rep in enumerate(self.replicas):
            # mid-decode slots hand off LIVE (KV pages + sampler
            # state) to siblings — zero re-prefill, zero replay; the
            # evacuation below covers the queued remainder, and any
            # failed migration simply rides out the graceful drain
            self._migrate_live(idx)
            harvested = rep.evacuate()      # queued -> siblings now,
            with self._lock:                # instead of riding out the
                self._stats["evacuations"] += 1   # drain wall
            if self._tele is not None:
                self._tele.on_evacuation(idx)
            self._requeue(idx, harvested)
            rep.stop(drain=True, timeout=drain_timeout)
            rep.start()
            if self._rec is not None:
                self._rec.record("restart", replica=idx)
            # requests the requeue parked under sibling backpressure
            # must not wait for a supervisor thread that may not be
            # running — the restarted replica can take them now
            self._drain_backlog()
            with self._lock:
                self._stats["restarts"] += 1
            self._publish_health()
