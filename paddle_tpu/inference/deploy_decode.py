"""Deployable generation: prefill + greedy-decode as StableHLO archives.

The reference deploys LMs by `save_inference_model` + AnalysisPredictor
driving the fused decode op per token. The TPU-native artifact is TWO
`jax.export` archives with the weights baked as constants:

- ``<prefix>.prefill``: ids [B, T] -> (first_token [B], KV caches)
- ``<prefix>.decode``:  (first_token, caches) -> generated ids [B, N]
  (the whole greedy loop as one serialized scan program)

A serving process needs only these files and jax — no model code, no
framework import. ``load_decode`` returns a generator handle.
"""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["export_decode", "load_decode", "DeployedGenerator"]


def export_decode(path_prefix, model, prompt_len, max_new_tokens,
                  batch=1, max_cache_len=None, eos_token_id=None,
                  weight_dtype=None):
    """Serialize this model's generation pipeline at fixed shapes
    (``batch`` x ``prompt_len`` prompts, ``max_new_tokens`` outputs —
    static shapes are the deployment contract, like the reference's
    baked feed shapes). Returns the two archive paths."""
    from jax import export as jax_export

    if max_cache_len is None:
        max_cache_len = prompt_len + max_new_tokens
    elif prompt_len + max_new_tokens > max_cache_len:
        # decode writes via lax.dynamic_update_slice, which CLAMPS
        # out-of-bounds starts — an undersized cache would silently
        # overwrite its last rows and emit wrong tokens (ADVICE r5 #5);
        # fail like GenerationMixin.generate does
        raise ValueError(
            f"prompt_len ({prompt_len}) + max_new_tokens "
            f"({max_new_tokens}) exceeds max_cache_len ({max_cache_len})")
    bundle = model._decode_bundle(max_cache_len, weight_dtype)
    init_caches, embed_fn, step_fn, head_fn, _ = bundle

    def prefill(ids):
        x0 = model._prefill_embed(ids, bundle)
        out, caches = step_fn(x0, init_caches(batch), jnp.int32(0))
        first = jnp.argmax(head_fn(out[:, -1:])[:, -1], -1)
        return first.astype(jnp.int32), caches

    def decode(first, caches):
        def body(carry, _):
            tok, cs, t, done = carry
            x = embed_fn(tok, t)
            out, cs2 = step_fn(x, cs, t)
            logits = head_fn(out)
            if logits.ndim == 3:
                logits = logits[:, -1]
            nxt = jnp.argmax(logits, -1).astype(jnp.int32)
            if eos_token_id is not None:
                nxt = jnp.where(done, jnp.int32(eos_token_id), nxt)
                done = done | (nxt == eos_token_id)
            return (nxt, cs2, t + 1, done), tok

        # an eos-first prefill must eos-pad the whole output, matching
        # the in-process generate() (ADVICE r5 #3)
        done = (first == eos_token_id) if eos_token_id is not None \
            else jnp.zeros((batch,), bool)
        carry = (first, caches, jnp.int32(prompt_len), done)
        _, toks = jax.lax.scan(body, carry, None, length=max_new_tokens)
        return jnp.transpose(toks, (1, 0))

    ids_aval = jax.ShapeDtypeStruct((batch, prompt_len), jnp.int32)
    first_aval, caches_aval = jax.eval_shape(prefill, ids_aval)

    def _export(fn, avals):
        jitted = jax.jit(fn)
        try:
            return jax_export.export(jitted, platforms=("cpu", "tpu"))(
                *avals)
        except Exception:
            return jax_export.export(jitted)(*avals)

    os.makedirs(os.path.dirname(os.path.abspath(path_prefix)),
                exist_ok=True)
    paths = []
    for name, fn, avals in (("prefill", prefill, (ids_aval,)),
                            ("decode", decode,
                             (first_aval, caches_aval))):
        exp = _export(fn, avals)
        path = f"{path_prefix}.{name}"
        with open(path, "wb") as f:
            f.write(exp.serialize())
        paths.append(path)
    with open(path_prefix + ".genmeta", "w") as f:
        json.dump({"format": "paddle_tpu-decode-v1",
                   "batch": batch, "prompt_len": prompt_len,
                   "max_new_tokens": max_new_tokens,
                   "max_cache_len": max_cache_len,
                   "eos_token_id": eos_token_id,
                   "weight_dtype": weight_dtype}, f)
    return tuple(paths)


class DeployedGenerator:
    """Runs a ``export_decode`` artifact: ids [B, T] -> [B, T + N]."""

    def __init__(self, path_prefix):
        from jax import export as jax_export
        with open(path_prefix + ".genmeta") as f:
            self.meta = json.load(f)
        with open(path_prefix + ".prefill", "rb") as f:
            self._prefill = jax_export.deserialize(f.read())
        with open(path_prefix + ".decode", "rb") as f:
            self._decode = jax_export.deserialize(f.read())

    def generate(self, input_ids):
        ids = np.asarray(input_ids).astype(np.int32)
        if ids.ndim == 1:
            ids = ids[None]
        B, T = ids.shape
        if (B, T) != (self.meta["batch"], self.meta["prompt_len"]):
            raise ValueError(
                f"archive serves shape ({self.meta['batch']}, "
                f"{self.meta['prompt_len']}), got ({B}, {T}) — export "
                f"per served shape (static-shape deployment contract)")
        first, caches = self._prefill.call(jnp.asarray(ids))
        new_ids = self._decode.call(first, caches)
        return np.concatenate([ids, np.asarray(new_ids)], axis=1)


def load_decode(path_prefix):
    return DeployedGenerator(path_prefix)
