"""Process-isolated replicas behind the typed wire transport
(ISSUE 12, ROADMAP item 4's architectural gate).

``ReplicaHost`` runs one ``ContinuousBatchingServer`` behind the
length-prefixed JSON protocol (inference/transport.py): submit /
wait / cancel / evacuate / stats / health / start / stop / kill over
request-reply frames, streamed tokens and journey events as push
frames, and a load DIGEST pushed on a heartbeat cadence.

``RemoteReplica`` is the client proxy implementing the exact surface
``ReplicaRouter`` consumes — so the router works UNCHANGED over any
mix of in-process server objects and remote processes:

- Routing reads stay LOCK-FREE: ``queue_depth`` / ``in_flight`` /
  ``preempt_pressure`` / ``prefix_sketch`` / ``health`` read the last
  pushed digest (plain attribute loads), never the wire. Staleness is
  the health signal: a digest older than ``draining_after_s`` reads
  ``draining`` (the router stops routing new traffic there), older
  than ``dead_after_s`` reads ``dead`` (the supervisor evacuates) —
  missed heartbeats ARE the failure detector, exactly the contract the
  in-process fleet only pretended to have.

- Every submitted request is MIRRORED client-side (prompt, budget,
  RESOLVED seed, absolute deadline, streamed tokens so far). When the
  host process actually dies (SIGKILL, not a polite ``kill()``), the
  proxy synthesizes the evacuation the corpse can no longer answer:
  requests that never streamed a token are harvested for bit-exact
  requeue on siblings (seeds were resolved at router submit), requests
  caught mid-decode flush their streamed partial to the waiter — the
  same split ``evacuate(flush_partials=True)`` performs in-process.

- The connection self-heals: a severed link (chaos ``net.*`` fires, a
  host restart) reconnects lazily on the next call, and the host
  forwards pushes to every live connection, so rids survive a
  reconnect (they live in the host server, not the socket).

``spawn_replica_host(factory)`` is the process-isolation entry point:
it spawns a child that builds the server from a picklable factory,
serves it, and reports the bound port — the unit the kill-drill
acceptance test SIGKILLs mid-decode.
"""
import collections
import threading
import time

import numpy as np

from ..reliability import DEAD, DRAINING, RetryPolicy, TransportError
from ..reliability.errors import (CallbackError, FrameError,
                                  MigrationError)
from ..telemetry.clock import MonotonicClock
from . import transport
from .transport import (decode_snapshot, encode_snapshot, jsonable,
                        marshal_error, unmarshal_error)

__all__ = ["ReplicaHost", "RemoteReplica", "spawn_replica_host"]

# ops whose handler may block (graceful drains, thread joins): each
# runs on its own short-lived thread so the connection's reader keeps
# servicing quick ops (submit/cancel/digest reads) meanwhile. The
# high-frequency blocking op — "wait", issued once per wait slice per
# outstanding request — runs on a small persistent pool instead:
# thread-per-call there would be continuous create/teardown churn on
# the serving hot path.
_THREADED_OPS = frozenset({"stop", "kill", "start", "shutdown"})

# ops that need the CALLING connection (not just the message): a
# migrate_out streams its binary page frames back on the same socket
# that carried the request, never as a broadcast
_CONN_OPS = frozenset({"migrate_out"})


class _WireJourney:
    """Host-side stand-in for a ``telemetry.Journey`` handle: every
    event the server emits through it is pushed over the wire (keyed
    by the client's trace id) and replayed into the client's real
    recorder — so a remote replica's admission/prefill/preempt/replay
    phases land on the SAME fleet timeline as local hops. Emission
    must never fail a serve tick: pushes are best-effort."""

    __slots__ = ("_host", "tid", "where")

    def __init__(self, host, tid, where):
        self._host = host
        self.tid = tid
        self.where = where

    def event(self, phase, /, **fields):
        self._host._push({"push": "journey", "tid": self.tid,
                          "phase": str(phase), "where": self.where,
                          "f": jsonable(fields)})

    def at(self, where):
        return _WireJourney(self._host, self.tid, where)


class ReplicaHost:
    """Serve one ``ContinuousBatchingServer`` over the wire protocol.

    >>> host = ReplicaHost(server).start()
    >>> rep = RemoteReplica(host.address)     # possibly in another
    >>> router = ReplicaRouter([rep, ...])    # process entirely

    The host owns the LISTENER and the heartbeat, not the server's
    lifecycle: ``start``/``stop``/``kill`` arrive as wire ops (the
    router drives them), and ``close()`` tears down only the network
    side. ``sever()`` is the drill hook: it drops every connection and
    pauses heartbeats — the network face of a crash — while the server
    keeps its state, exactly what a SIGKILL leaves behind minus the
    process exit.
    """

    def __init__(self, server, host="127.0.0.1", port=0,
                 heartbeat_s=0.02, fault_injector=None):
        import socket
        self.server = server
        self.heartbeat_s = float(heartbeat_s)
        self._faults = fault_injector
        tele = getattr(server, "telemetry", None)
        self._registry = tele.registry if (
            tele is not None and getattr(tele, "enabled", False)) \
            else None
        self._listener = socket.create_server((host, port))
        self._listener.settimeout(0.2)
        self.address = (host, self._listener.getsockname()[1])
        self._conns = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._shutdown = threading.Event()
        self._hb_pause = threading.Event()
        self._hb_seq = 0
        self.heartbeat_errors = 0
        self.last_heartbeat_error = None
        # wait() replies may be lost on a chaotic wire; results are
        # stashed so a retried wait for the same rid is idempotent
        # (bounded: oldest delivery records fall off)
        self._delivered = collections.OrderedDict()
        self._dlock = threading.Lock()
        from concurrent.futures import ThreadPoolExecutor
        self._wait_pool = ThreadPoolExecutor(
            max_workers=16, thread_name_prefix="replica-host-wait")
        # per-rid count of tokens already pushed: every token frame
        # carries its stream OFFSET so a client behind a lossy wire
        # can tell a dropped chunk from the next one (bounded with
        # the same cap as _delivered)
        self._streamed = collections.OrderedDict()
        # inbound migration page frames, parked per transfer id until
        # the migrate_in op closes the set (bounded: an abandoned
        # transfer — client died mid-stream — ages out, never leaks)
        self._mig_in = collections.OrderedDict()
        self._threads = []

    @property
    def port(self):
        return self.address[1]

    # -------------------------------------------------------- lifecycle
    def start(self):
        """Start the accept + heartbeat threads; returns self."""
        for fn in (self._accept_loop, self._heartbeat_loop):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def close(self):
        """Tear down the network side (listener, connections,
        heartbeats). The server object is untouched."""
        self._stop.set()
        self._shutdown.set()
        self._wait_pool.shutdown(wait=False, cancel_futures=True)
        try:
            self._listener.close()
        except OSError:
            pass            # already closed by a prior close()
        with self._lock:
            conns, self._conns = self._conns, []
        for conn in conns:
            conn.close()

    def sever(self):
        """Drill hook: cut every connection and pause heartbeats — the
        network signature of a crash, with the server state intact for
        a post-drill autopsy. ``unsever()`` resumes heartbeats (new
        connections are accepted throughout)."""
        self._hb_pause.set()
        with self._lock:
            conns, self._conns = self._conns, []
        for conn in conns:
            conn.close()

    def unsever(self):
        self._hb_pause.clear()

    def pause_heartbeats(self):
        """Drill hook: stop pushing digests while keeping connections
        open — the network signature of a FROZEN (not crashed) host,
        which is exactly what the client's staleness walk
        (fresh -> draining -> dead) exists to catch."""
        self._hb_pause.set()

    def resume_heartbeats(self):
        self._hb_pause.clear()

    def wait_shutdown(self, timeout=None):
        """Block until a ``shutdown`` op (or ``close()``) — the child
        process entry point parks here."""
        return self._shutdown.wait(timeout)

    # ------------------------------------------------------------ loops
    def _accept_loop(self):
        import socket
        while not self._stop.is_set():
            try:
                sock, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return          # listener closed: shutting down
            conn = transport.Connection(sock,
                                        fault_injector=self._faults,
                                        registry=self._registry)
            with self._lock:
                self._conns.append(conn)
            threading.Thread(target=self._conn_loop, args=(conn,),
                             daemon=True).start()

    def _conn_loop(self, conn):
        while not self._stop.is_set():
            try:
                msg = conn.recv(timeout=0.5)
            except TimeoutError:
                continue
            except FrameError:
                # ONE corrupt frame: the stream is still in sync and no
                # call can be attributed, so drop it and keep serving —
                # a fuzzer's garbage must never wedge the host loop
                continue
            except TransportError:
                break
            if not isinstance(msg, dict):
                continue        # parsed-but-garbage payload: drop
            op, cid = msg.get("op"), msg.get("id")
            if not isinstance(op, str) or cid is None:
                continue
            if op == "wait":
                try:
                    self._wait_pool.submit(self._handle, conn, cid,
                                           op, msg)
                except RuntimeError:
                    break       # pool shut down: host is closing
            elif op in _THREADED_OPS:
                threading.Thread(target=self._handle,
                                 args=(conn, cid, op, msg),
                                 daemon=True).start()
            else:
                self._handle(conn, cid, op, msg)
        self._drop_conn(conn)

    def _heartbeat_loop(self):
        while not self._stop.wait(self.heartbeat_s):
            if self._hb_pause.is_set():
                continue
            try:
                digest = self._digest()
            except Exception as e:
                # a transient server-side error (stop/restart race, a
                # stats value jsonable chokes on) must not kill the
                # heartbeat thread — silenced heartbeats read as a
                # DEAD host and trigger a spurious evacuation
                self.heartbeat_errors += 1
                self.last_heartbeat_error = e
                continue
            self._push({"push": "digest", "d": digest})

    def _digest(self):
        srv = self.server
        self._hb_seq += 1
        return {"seq": self._hb_seq,
                "queue_depth": int(srv.queue_depth()),
                "in_flight": int(srv.in_flight()),
                "preempt_pressure": int(srv.preempt_pressure()),
                "health": srv.health,
                # disaggregated placement (ISSUE 20): the role rides
                # every digest so the router's placement scan needs no
                # extra RPC; pre-role servers read as "hybrid"
                "role": str(getattr(srv, "role", "hybrid")),
                "sketch": [int(fp) for fp in srv.prefix_sketch()],
                "stats": jsonable(dict(srv.stats)),
                # goodput ratio + MFU (ISSUE 13): routing-side views
                # see per-replica utilization from the heartbeat
                # alone, no registry pull ({} when neither the ledger
                # nor the cost catalog is wired)
                "util": jsonable(srv.utilization())
                if callable(getattr(srv, "utilization", None)) else {}}

    def _push(self, msg):
        """Best-effort broadcast to every live connection (token
        chunks, journey events, digests). A connection that fails mid-
        push is dropped — its client will reconnect or be declared
        dead by staleness."""
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.send(msg)
            except FrameError:
                return      # push too big for one frame: skip it for
            #                 every client (stream untouched, conn fine)
            except (TransportError, OSError):
                self._drop_conn(conn)

    def _drop_conn(self, conn):
        conn.close()
        with self._lock:
            if conn in self._conns:
                self._conns.remove(conn)

    # --------------------------------------------------------- dispatch
    def _handle(self, conn, cid, op, msg):
        try:
            fn = getattr(self, "_op_" + op, None)
            if fn is None:
                raise ValueError(f"unknown wire op {op!r}")
            result = fn(conn, msg) if op in _CONN_OPS else fn(msg)
        except Exception as e:
            reply = {"re": cid, "ok": False, "err": marshal_error(e)}
        else:
            reply = {"re": cid, "ok": True, "r": result}
        try:
            conn.send(reply)
        except FrameError as e:
            # the REPLY itself was too big for one frame (e.g. a huge
            # evacuate payload): the send refused before touching the
            # socket, so the stream is intact — fail ONE call with the
            # typed error instead of severing a healthy connection
            try:
                conn.send({"re": cid, "ok": False,
                           "err": marshal_error(e)})
            except (TransportError, OSError):
                self._drop_conn(conn)
        except (TransportError, OSError):
            self._drop_conn(conn)

    def _op_hello(self, msg):
        return {"page_size": self.server.page_size,
                "digest": self._digest()}

    def _op_ping(self, msg):
        return "pong"

    def _op_submit(self, msg):
        srv = self.server
        journey = None
        tid = msg.get("tid")
        if tid is not None:
            journey = _WireJourney(self, tid,
                                   msg.get("where") or "replica")
        rid = srv.submit(
            np.asarray(msg["ids"], np.int32),
            max_new_tokens=int(msg["n"]), seed=msg.get("seed"),
            on_token=self._forwarder, deadline_s=msg.get("deadline_s"),
            priority=int(msg.get("priority") or 0), journey=journey)
        seed = msg.get("seed")
        if seed is None:
            # the server defaulted it; the client mirror needs the
            # RESOLVED value so a synthesized requeue draws the
            # identical sampling chain. Mirrors the default-seed rule
            # at ContinuousBatchingServer.submit — keep in sync
            # (tests/test_remote_replica.py pins the parity)
            seed = srv._seed + rid
        return {"rid": int(rid), "seed": int(seed)}

    def _forwarder(self, rid, tokens):
        # every request streams over the wire whether or not the client
        # attached an on_token: the mirror's token log is what makes a
        # SIGKILL's partials flushable. Each frame carries its stream
        # OFFSET so a chunk lost to chaos cannot leave a silent GAP in
        # the client's log — the mirror keeps a bit-exact contiguous
        # prefix, whatever the wire drops. Never raises (a dead client
        # must not fail the request on a live host).
        rid = int(rid)
        with self._dlock:
            off = self._streamed.get(rid, 0)
            self._streamed[rid] = off + len(tokens)
            # true LRU (not insertion order): evicting a rid that is
            # STILL streaming would restart its offset at 0 and let a
            # later chunk stitch a gap into the client's mirror — with
            # move-to-end, eviction needs 4096 other rids to push
            # between two of its chunks, impossible for a server whose
            # active streams are bounded by max_slots
            self._streamed.move_to_end(rid)
            while len(self._streamed) > 4096:
                self._streamed.popitem(last=False)
        self._push({"push": "tokens", "rid": rid, "off": off,
                    "toks": [int(t) for t in tokens]})

    def _op_wait(self, msg):
        rid = int(msg["rid"])
        with self._dlock:
            hit = self._delivered.get(rid)
        if hit is not None:
            kind, val = hit
            if kind == "err":
                raise unmarshal_error(val)
            return val
        try:
            out = self.server.wait(rid, timeout=float(msg["timeout"]))
        except Exception as e:
            # a plain TimeoutError is a not-finished-yet probe and must
            # not be stashed; everything else is terminal (the server
            # popped the rid — DeadlineExceeded subclasses TimeoutError
            # but is exactly such a terminal outcome) and is stashed so
            # a retried wait after a lost reply sees the same verdict
            if type(e) is TimeoutError:
                raise
            self._stash(rid, ("err", marshal_error(e)))
            raise
        result = [int(t) for t in out]
        self._stash(rid, ("ok", result))
        return result

    def _stash(self, rid, record):
        with self._dlock:
            self._delivered[rid] = record
            while len(self._delivered) > 4096:
                self._delivered.popitem(last=False)

    def _op_cancel(self, msg):
        return bool(self.server.cancel(int(msg["rid"])))

    def _op_evacuate(self, msg):
        srv = self.server
        harvested = srv.evacuate(
            flush_partials=bool(msg.get("flush_partials")))
        now = srv._clock.now()
        out = []
        for item in harvested:
            rem = None if item.deadline is None \
                else max(0.0, item.deadline - now)
            out.append({"rid": int(item.rid),
                        "ids": [int(t) for t in item.ids],
                        "budget": int(item.budget),
                        "seed": int(item.seed),
                        "deadline_s": rem,
                        "priority": int(item.priority)})
        return out

    def _op_abandon(self, msg):
        return bool(self.server.abandon(int(msg["rid"]),
                                        unmarshal_error(msg["err"])))

    # --------------------------------------------- live KV-page migration
    def _op_migrate_out(self, conn, msg):
        """Pause one live request (mid-decode, or mid-prefill for the
        ISSUE-20 handoff) and stream its KV pages BACK to the calling
        connection as binary page frames (one frame per page, K and V
        stacked, sha256-checked by the transport), then reply with the
        serialized migration state. The slot stays paused until the
        caller settles with migrate_finish / migrate_abort; a failure
        streaming the pages aborts HERE (the caller may never be able
        to ask) and fails the call typed. ``partial=True`` is the
        NON-pausing pipelined pull: one bounded batch of complete
        mid-prefill pages streams back and the slot keeps chunking —
        nothing to abort on failure."""
        rid = int(msg["rid"])
        xid = msg.get("xid")
        partial = bool(msg.get("partial"))
        state, payloads = self.server.migrate_out(
            rid, partial=partial,
            from_page=int(msg.get("from_page") or 0))
        try:
            for i, p in enumerate(payloads):
                a = np.ascontiguousarray(np.stack(p))   # [2, L, pg, ...]
                conn.send_pages(
                    {"push": "pages", "xid": xid, "i": i,
                     "n": len(payloads), "shape": list(a.shape),
                     "dtype": str(a.dtype)}, a.tobytes())
        except Exception as e:
            if not partial:
                self.server.migrate_abort(rid)
            raise MigrationError(
                f"page stream to the caller failed at frame "
                f"{i}/{len(payloads)}: {e!r}") from e
        return jsonable(state)

    def _op_migrate_page(self, msg):
        """One inbound migration page frame (fire-and-forget, id 0):
        park the raw payload under its transfer id until migrate_in
        closes the set. Malformed frames are dropped — the completeness
        check in _op_migrate_in degrades that attempt typed."""
        xid = msg.get("xid")
        buf = msg.get("_payload")
        if xid is None or buf is None:
            return False
        a = np.frombuffer(buf, dtype=np.dtype(msg["dtype"]))
        a = a.reshape(msg["shape"])
        with self._dlock:
            slot = self._mig_in.setdefault(xid, {})
            slot[int(msg["i"])] = a
            self._mig_in.move_to_end(xid)
            while len(self._mig_in) > 8:
                self._mig_in.popitem(last=False)
        return True

    def _op_migrate_in(self, msg):
        """Commit a migration INTO this host's server: reassemble the
        parked page payloads, restore through the server's normal admit
        path, and continue the token stream at the source's offset (the
        client mirror already holds the pre-migration prefix, so the
        forwarder must not restart at 0)."""
        xid = msg.get("xid")
        state = dict(msg["state"])
        with self._dlock:
            got = self._mig_in.pop(xid, None) or {}
        n = len(state.get("sha256") or ())
        payloads = [got.get(i) for i in range(n)]
        if n == 0 or any(p is None for p in payloads):
            raise MigrationError(
                f"page frames lost on the wire: {len(got)}/{n} arrived "
                f"for transfer {xid!r}")
        journey = None
        tid = msg.get("tid")
        if tid is not None:
            journey = _WireJourney(self, tid,
                                   msg.get("where") or "replica")
        rid = self.server.migrate_in(state, payloads,
                                     on_token=self._forwarder,
                                     journey=journey)
        with self._dlock:
            self._streamed[int(rid)] = int(state.get("streamed") or 0)
            self._streamed.move_to_end(int(rid))
        return {"rid": int(rid)}

    def _op_migrate_in_begin(self, msg):
        """Open a pipelined (staged) restore on this host's server —
        the target half of a disaggregated prefill handoff. Replies
        with the transfer handle the page batches and the commit key
        off."""
        return {"handle": int(self.server.migrate_in_begin(
            dict(msg["state"])))}

    def _op_migrate_in_pages(self, msg):
        """Land one pipelined page batch: reassemble whatever frames of
        the batch survived the wire (parked by ``_op_migrate_page``
        under the transfer id) and scatter each surviving page at its
        absolute index — holes are REPORTED, not fatal, so the pump
        re-ships exactly what the storm ate and the commit's coverage
        check stays the single source of truth."""
        xid = msg.get("xid")
        with self._dlock:
            got = self._mig_in.pop(xid, None) or {}
        sha = list(msg.get("sha256") or ())
        base = int(msg.get("base") or 0)
        handle = int(msg["handle"])
        landed, lost = [], []
        for i in range(len(sha)):
            p = got.get(i)
            if p is None:
                lost.append(base + i)
                continue
            self.server.migrate_in_pages(handle, base + i, [p],
                                         [sha[i]])
            landed.append(base + i)
        return {"landed": landed, "lost": lost}

    def _op_migrate_in_commit(self, msg):
        """Close a pipelined restore: reassemble the parked closing
        frames (ALL of them must have arrived — the closing batch is
        the commit point, holes degrade the attempt typed with the
        staging kept), commit through the server, and continue the
        token stream at the source's offset exactly like
        ``_op_migrate_in``."""
        xid = msg.get("xid")
        state = dict(msg["state"])
        with self._dlock:
            got = self._mig_in.pop(xid, None) or {}
        n = len(state.get("sha256") or ())
        payloads = [got.get(i) for i in range(n)]
        if any(p is None for p in payloads):
            raise MigrationError(
                f"closing page frames lost on the wire: "
                f"{sum(p is not None for p in payloads)}/{n} arrived "
                f"for transfer {xid!r}")
        journey = None
        tid = msg.get("tid")
        if tid is not None:
            journey = _WireJourney(self, tid,
                                   msg.get("where") or "replica")
        rid = self.server.migrate_in_commit(
            int(msg["handle"]), state, payloads,
            on_token=self._forwarder, journey=journey)
        with self._dlock:
            self._streamed[int(rid)] = int(state.get("streamed") or 0)
            self._streamed.move_to_end(int(rid))
        return {"rid": int(rid)}

    def _op_migrate_in_abort(self, msg):
        return bool(self.server.migrate_in_abort(int(msg["handle"])))

    def _op_migrate_finish(self, msg):
        rid = int(msg["rid"])
        self.server.migrate_finish(rid)
        with self._dlock:
            self._streamed.pop(rid, None)
        return True

    def _op_migrate_abort(self, msg):
        return bool(self.server.migrate_abort(int(msg["rid"])))

    def _op_fetch_tokens(self, msg):
        """Backfill a gap the wire chewed into a client's token stream
        (ISSUE 18 satellite): re-push this request's emitted tokens
        from ``off`` onward as a normal offset-carrying token frame,
        read from whatever still remembers them — the live slot, the
        preempted parking lot, the finished-result map, or the wait
        delivery stash. Returns the number of tokens re-pushed (None:
        rid unknown here, nothing to repair from)."""
        rid = int(msg["rid"])
        off = max(0, int(msg.get("off") or 0))
        srv = self.server
        toks = None
        with self._dlock:
            hit = self._delivered.get(rid)
        if hit is not None and hit[0] == "ok":
            toks = list(hit[1])
        if toks is None:
            with srv._lock:
                for st in srv._slots:
                    if st is not None and st.rid == rid:
                        toks = [int(t) for t in st.emitted]
                        break
                if toks is None:
                    for rec in srv._preempted:
                        if rec.rid == rid:
                            toks = [int(t) for t in rec.emitted]
                            break
                if toks is None:
                    out = srv._results.get(rid)
                    if out is not None:
                        toks = [int(t) for t in out]
        if toks is None:
            return None
        back = [int(t) for t in toks[off:]]
        if back:
            self._push({"push": "tokens", "rid": rid, "off": off,
                        "toks": back})
        return len(back)

    def _op_stats(self, msg):
        return jsonable(dict(self.server.stats))

    def _op_health(self, msg):
        return self.server.health

    def _op_pool_balance(self, msg):
        bal = self.server.pool_balance()
        if bal is None:
            return None
        return {"free": bal[0], "live": bal[1], "pinned": bal[2],
                "cached": bal[3], "preempted": bal.preempted,
                "preemptions": bal.preemptions}

    def _op_snapshot(self, msg):
        tele = getattr(self.server, "telemetry", None)
        if tele is None or not getattr(tele, "enabled", False):
            return None
        return encode_snapshot(tele.registry.snapshot())

    def _op_postmortems(self, msg):
        return jsonable(self.server.postmortems())

    def _op_start(self, msg):
        if self.server._thread is None:
            self.server.start()
        return True

    def _op_stop(self, msg):
        self.server.stop(timeout=float(msg.get("timeout") or 60.0),
                         drain=bool(msg.get("drain")))
        return True

    def _op_kill(self, msg):
        self.server.kill(timeout=float(msg.get("timeout") or 60.0))
        return True

    def _op_shutdown(self, msg):
        # reply is sent by _handle after we return; close on a helper
        # thread so the farewell frame gets out first
        def later():
            time.sleep(0.05)
            self.close()
        threading.Thread(target=later, daemon=True).start()
        return True


class _Call:
    __slots__ = ("evt", "result", "err", "on_reply", "conn")

    def __init__(self, on_reply=None, conn=None):
        self.evt = threading.Event()
        self.result = None
        self.err = None
        self.on_reply = on_reply
        self.conn = conn              # the connection that carried it:
        #                               a dying conn settles only ITS
        #                               calls, never a successor's


class _Mirror:
    """Client-side shadow of one in-flight remote request — everything
    a synthesized evacuation needs when the host can no longer answer."""

    __slots__ = ("rid", "ids", "budget", "seed", "on_token", "deadline",
                 "priority", "journey", "tid", "tokens", "done")

    def __init__(self, rid, ids, budget, seed, on_token, deadline,
                 priority, journey, tid):
        self.rid = rid
        self.ids = ids
        self.budget = budget
        self.seed = seed
        self.on_token = on_token
        self.deadline = deadline      # CLIENT-clock absolute, or None
        self.priority = priority
        self.journey = journey
        self.tid = tid
        self.tokens = []              # streamed so far (wire pushes)
        self.done = False


class _Harvested:
    """One synthesized/decoded evacuation entry — duck-compatible with
    the server's ``_Pending`` as far as the router reads it."""

    __slots__ = ("rid", "ids", "budget", "seed", "on_token", "deadline",
                 "priority", "journey")

    def __init__(self, rid, ids, budget, seed, on_token, deadline,
                 priority, journey):
        self.rid = rid
        self.ids = ids
        self.budget = budget
        self.seed = seed
        self.on_token = on_token
        self.deadline = deadline
        self.priority = priority
        self.journey = journey


class RemoteReplica:
    """Client proxy speaking the wire protocol; implements the exact
    replica surface ``ReplicaRouter`` consumes (submit / wait / cancel
    / evacuate / health / queue_depth / in_flight / preempt_pressure /
    prefix_sketch / abandon / postmortems / start / stop / kill /
    ``page_size``), so a router routes over it UNCHANGED.

    ``draining_after_s`` / ``dead_after_s`` bound digest staleness:
    past the first the replica stops taking new traffic, past the
    second the supervisor treats it as dead and evacuates. Both must
    comfortably exceed the host's ``heartbeat_s`` (defaults assume the
    0.02 s default cadence; scale them together).

    ``registry`` (``telemetry.MetricRegistry``) publishes the wire
    counters (``net_frames_total{dir}`` / ``net_bytes_total{dir}`` /
    ``net_transport_errors_total``), ``net_call_seconds`` round-trip
    latency, and ``net_heartbeats_total``.

    ``fault_injector`` arms the ``net.*`` chaos points on this
    client's connections (see ``reliability.faults``). Construction
    dials the host once and raises ``TransportError`` if it cannot —
    arm probabilistic storms after the fleet is built (or window them
    with ``start=``), the way the chaos suites do.
    """

    telemetry = None        # fleet_snapshot merges via registry_snapshot

    def __init__(self, address, clock=None, fault_injector=None,
                 registry=None, connect_timeout=5.0,
                 draining_after_s=0.25, dead_after_s=0.75,
                 call_timeout_s=30.0, reconnect_min_s=0.05, name=None):
        self.address = (str(address[0]), int(address[1]))
        self.name = name or f"{self.address[0]}:{self.address[1]}"
        self._clock = clock if clock is not None else MonotonicClock()
        self._faults = fault_injector
        self._registry = registry if (
            registry is not None and getattr(registry, "enabled", False)
        ) else None
        self.connect_timeout = float(connect_timeout)
        self.draining_after_s = float(draining_after_s)
        self.dead_after_s = float(dead_after_s)
        self.call_timeout_s = float(call_timeout_s)
        self.snapshot_timeout_s = 2.0
        self.reconnect_min_s = float(reconnect_min_s)
        self._h_call = self._c_hb = None
        if self._registry is not None:
            self._h_call = self._registry.histogram(
                "net_call_seconds",
                "Wire RPC round-trip latency (request frame out to "
                "reply frame in)")
            self._c_hb = self._registry.counter(
                "net_heartbeats_total",
                "Replica load digests received over the wire")
        self._conn = None
        self._conn_lock = threading.RLock()
        self._last_attempt = 0.0
        self._closed = False
        self._calls = {}
        self._id_lock = threading.Lock()
        self._next_id = 1
        self._state_lock = threading.RLock()
        self._mirror = {}             # replica rid -> _Mirror
        self._journeys = {}           # tid -> Journey handle
        self._results = {}            # locally settled (synth evacuate)
        self._failures = {}
        # token pushes racing ahead of their submit REPLY (the host's
        # serve thread streams independently of the conn thread that
        # answers the submit): parked here until the mirror registers,
        # bounded — unclaimed entries are dropped oldest-first
        self._early_tokens = collections.OrderedDict()  # rid -> [msg]
        # binary page frames for in-flight migrate_out calls, parked
        # per transfer id until the state reply closes the set
        self._mig_pages = {}          # xid -> {page index: ndarray}
        # retry/backoff for the migration wire ops (transient failures
        # only — a typed host refusal never retries)
        self.migrate_retry = RetryPolicy(base_delay_s=0.02,
                                         max_delay_s=0.25)
        self.migrate_attempts = 3
        self._digest = None
        self._sketch = frozenset()
        self._last_hb = -1e9
        self.page_size = None
        self._thread = None           # router start()/stop() contract
        self._thread_error = None     # router wait() identity contract
        self._connect()               # raises TransportError on failure

    # ------------------------------------------------------- connection
    def _connect(self):
        conn = transport.connect(self.address,
                                 timeout=self.connect_timeout,
                                 fault_injector=self._faults,
                                 registry=self._registry)
        try:
            conn.send({"id": 0, "op": "hello"})
            deadline = time.monotonic() + self.connect_timeout
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TransportError(
                        f"{self.name}: no hello reply in "
                        f"{self.connect_timeout}s")
                try:
                    msg = conn.recv(timeout=remaining)
                except (TimeoutError, FrameError) as e:
                    raise TransportError(
                        f"{self.name}: handshake failed: {e}") from e
                if isinstance(msg, dict) and msg.get("re") == 0:
                    break
                self._dispatch_push(msg)    # digests may arrive first
        except TransportError:
            conn.close()
            raise
        if not msg.get("ok"):
            conn.close()
            raise TransportError(
                f"{self.name}: hello refused: {msg.get('err')}")
        hello = msg["r"]
        self.page_size = hello.get("page_size")
        with self._conn_lock:
            self._conn = conn
            # _thread_error is NOT cleared on reconnect: the router's
            # wait() path discriminates stale-vs-real errors by
            # __cause__ IDENTITY with this attribute, and a waiter
            # mid-raise must still match it — a live connection (not a
            # None error) is what marks the proxy healthy again
        self._on_digest(hello.get("digest"))
        threading.Thread(target=self._reader, args=(conn,),
                         daemon=True).start()
        return conn

    def _ensure_conn(self):
        with self._conn_lock:
            if self._closed:
                raise TransportError(f"{self.name}: client closed")
            conn = self._conn
            if conn is not None and not conn.closed:
                return conn
            now = time.monotonic()
            if now - self._last_attempt < self.reconnect_min_s:
                err = TransportError(
                    f"{self.name}: disconnected (reconnect backoff)")
                err.__cause__ = self._thread_error
                raise err
            self._last_attempt = now
            return self._connect()

    def _reader(self, conn):
        while not self._closed:
            try:
                msg = conn.recv(timeout=0.5)
            except TimeoutError:
                continue
            except FrameError:
                continue            # one corrupt frame: stream resynced
            except TransportError as e:
                self._on_disconnect(conn, e)
                return
            if isinstance(msg, dict) and "re" in msg:
                self._settle(msg)
            else:
                self._dispatch_push(msg)
        self._on_disconnect(conn, TransportError(
            f"{self.name}: client closed"))

    def _settle(self, msg):
        call = self._calls.get(msg.get("re"))
        if call is None:
            return                  # reply to a timed-out call: drop
        if msg.get("ok"):
            call.result = msg.get("r")
            if call.on_reply is not None:
                # runs IN the reader so a mirror is registered before
                # any later push frame for the same rid is processed
                call.on_reply(call.result)
        else:
            call.err = unmarshal_error(msg.get("err") or {})
        call.evt.set()

    def _dispatch_push(self, msg):
        if not isinstance(msg, dict):
            return
        kind = msg.get("push")
        if kind == "digest":
            self._on_digest(msg.get("d"))
        elif kind == "tokens":
            self._on_tokens(msg)
        elif kind == "journey":
            self._on_journey(msg)
        elif kind == "pages":
            self._on_pages(msg)

    def _on_pages(self, msg):
        """One binary page frame for an in-flight migrate_out: park it
        under its transfer id. Frames for unknown transfer ids (an
        aborted or retried attempt, another client's migration riding
        the broadcast path) are dropped; a malformed header drops ONE
        frame and the completeness check downstream degrades that
        attempt typed."""
        xid = msg.get("xid")
        buf = msg.get("_payload")
        with self._state_lock:
            slot = self._mig_pages.get(xid)
            if slot is None or buf is None:
                return
            try:
                a = np.frombuffer(buf, dtype=np.dtype(msg["dtype"]))
                slot[int(msg["i"])] = a.reshape(msg["shape"])
            except Exception:
                return

    def _on_digest(self, d):
        if not isinstance(d, dict):
            return
        self._sketch = frozenset(d.get("sketch") or ())
        self._digest = d
        self._last_hb = self._clock.now()
        if self._c_hb is not None:
            self._c_hb.inc()

    def _on_tokens(self, msg):
        with self._state_lock:
            m = self._mirror.get(msg.get("rid"))
            if m is None:
                # no mirror YET: either this push raced ahead of the
                # submit reply (park it; the reply's on_reply hook
                # drains the parked frames in order) or the rid is
                # truly foreign (dropped submit reply / another
                # client) and the bounded buffer ages it out
                rid = msg.get("rid")
                if rid is not None:
                    parked = self._early_tokens.setdefault(rid, [])
                    if len(parked) < 32:
                        # per-rid cap too: a FOREIGN stream (another
                        # client's rid, broadcast to every connection)
                        # must not park its whole token log here
                        parked.append(msg)
                    while len(self._early_tokens) > 256:
                        self._early_tokens.popitem(last=False)
                return
            if m.done:
                return              # already settled locally
            toks = list(msg.get("toks") or ())
            have = len(m.tokens)
            off = msg.get("off")
            off = have if off is None else int(off)
            if off > have:
                # an earlier chunk was lost to the wire: appending this
                # one would punch a silent GAP into the partial (and the
                # user's stream). Keep the contiguous prefix — and ask
                # the host to BACKFILL from its own emitted-token log
                # (fire-and-forget: we are ON the reader thread; the
                # repair arrives as a normal offset-carrying token push
                # that stitches the prefix back together, re-covering
                # this chunk's range too). Re-asked on every subsequent
                # out-of-order chunk, so a repair the storm also eats
                # is retried for free.
                self._post("fetch_tokens", rid=int(msg["rid"]),
                           off=have)
                return
            toks = [int(t) for t in toks[have - off:]]
            if not toks:
                return              # duplicate/overlapping chunk
            m.tokens.extend(toks)
            cb = m.on_token
            if len(m.tokens) >= m.budget:
                # the stream just delivered the full budget: settle the
                # request locally so a client that never calls wait()
                # (pure streaming consumer) does not pin its mirror
                # forever, and a later wait() returns without a wire
                # round trip. (An early-EOS finish below budget still
                # settles via wait(); _results/_failures are bounded
                # for the never-waited case.)
                self._mirror.pop(msg["rid"], None)
                m.done = True
                self._journeys.pop(m.tid, None)
                self._results[msg["rid"]] = np.asarray(
                    m.tokens[:m.budget], np.int32)
                self._bound_settled_locked()
        if cb is None:
            return
        try:
            cb(msg["rid"], np.asarray(toks, np.int32))
        except Exception as e:
            # mirror the in-process contract: a poisoned stream fails
            # exactly ITS request, typed, and never kills the reader
            err = CallbackError([(msg["rid"], e)],
                                what="on_token callback")
            with self._state_lock:
                m = self._mirror.pop(msg["rid"], None)
                if m is not None:
                    m.done = True
                    self._journeys.pop(m.tid, None)
                self._failures[msg["rid"]] = err
            self._post("cancel", rid=int(msg["rid"]))

    def _on_journey(self, msg):
        handle = self._journeys.get(msg.get("tid"))
        if handle is None:
            return
        fields = msg.get("f") or {}
        try:
            handle._rec.event(handle.tid, str(msg.get("phase")),
                              str(msg.get("where") or handle.where),
                              **{str(k): v for k, v in fields.items()})
        except Exception:
            return      # a debug artifact must never wedge the reader

    def _on_disconnect(self, conn, err):
        conn.close()
        with self._conn_lock:
            if conn is self._conn:
                self._conn = None
                self._thread_error = err
        # unblock the calls THIS connection carried — a call already
        # riding a reconnected successor must not be spuriously failed
        # by the old reader thread's dying gasp
        for call in list(self._calls.values()):
            if call.conn is conn and not call.evt.is_set():
                call.err = err
                call.evt.set()

    # ------------------------------------------------------------ calls
    def _call(self, op, reply_timeout=None, on_reply=None, **args):
        """One request-reply round trip. ``reply_timeout`` bounds the
        CLIENT-side wait for the reply frame (wire-op arguments like a
        remote wait's ``timeout`` travel in ``args``)."""
        conn = self._ensure_conn()
        with self._id_lock:
            cid = self._next_id
            self._next_id += 1
        call = _Call(on_reply, conn=conn)
        self._calls[cid] = call
        t0 = time.monotonic()
        try:
            conn.send({"id": cid, "op": op, **args})
            budget = self.call_timeout_s if reply_timeout is None \
                else reply_timeout
            if not call.evt.wait(budget):
                raise TimeoutError(
                    f"{self.name}: {op} got no reply in {budget:.3g}s "
                    f"(frame lost or host stalled)")
        finally:
            self._calls.pop(cid, None)
        if call.err is not None:
            raise call.err
        if self._h_call is not None:
            self._h_call.observe(time.monotonic() - t0)
        return call.result

    def _post(self, op, **args):
        """Fire-and-forget wire op from the READER thread (its reply,
        addressed to the reserved id 0, is dropped by ``_settle``) — a
        blocking ``_call`` here would deadlock on the reader itself."""
        conn = self._conn
        if conn is None:
            return
        try:
            conn.send({"id": 0, "op": op, **args})
        except (TransportError, OSError):
            pass        # host unreachable: the local outcome stands

    def ping(self):
        """One wire round trip; returns its latency in seconds (the
        router bench's per-call overhead probe)."""
        t0 = time.monotonic()
        self._call("ping")
        return time.monotonic() - t0

    # ---------------------------------------------------- client surface
    def submit(self, input_ids, max_new_tokens=32, seed=None,
               on_token=None, deadline_s=None, priority=0,
               journey=None):
        """Submit one prompt to the remote server; returns the REMOTE
        request id. Same contract as
        ``ContinuousBatchingServer.submit`` — deadlines travel as
        remaining seconds and re-anchor on the host's clock; the
        resolved seed comes back with the reply so a synthesized
        failover requeue replays the identical sampling chain."""
        ids = np.asarray(input_ids).astype(np.int32).reshape(-1)
        tid = getattr(journey, "tid", None)
        where = getattr(journey, "where", None)
        if tid is not None:
            self._journeys[tid] = journey
        deadline = None if deadline_s is None \
            else self._clock.now() + float(deadline_s)

        def record(reply):
            with self._state_lock:
                self._mirror[reply["rid"]] = _Mirror(
                    reply["rid"], ids, int(max_new_tokens),
                    int(reply["seed"]), on_token, deadline,
                    int(priority), journey, tid)
                parked = self._early_tokens.pop(reply["rid"], ())
            for pm in parked:       # pushes that raced this reply
                self._on_tokens(pm)

        try:
            reply = self._call(
                "submit", ids=[int(t) for t in ids],
                n=int(max_new_tokens), seed=seed,
                deadline_s=deadline_s, priority=int(priority),
                tid=tid, where=where, on_reply=record)
        except BaseException:
            if tid is not None:
                self._journeys.pop(tid, None)
            raise
        return reply["rid"]

    def wait(self, rid, timeout=120.0):
        """Block until ``rid`` finishes; returns its new tokens.
        Results synthesized locally (a flushed partial from a dead
        host) win; otherwise the wire is polled in bounded slices so a
        reply lost to chaos costs one slice, not the whole timeout."""
        deadline = time.monotonic() + timeout
        while True:
            with self._state_lock:
                if rid in self._results:
                    self._settle_mirror(rid)
                    return self._results.pop(rid)
                if rid in self._failures:
                    self._settle_mirror(rid)
                    raise self._failures.pop(rid)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"request {rid} not finished in {timeout}s")
            if self._conn is None:
                # host unreachable: hold the waiter (like a dead serve
                # thread) — the supervisor's failover settles the rid.
                # __cause__ IDENTITY with _thread_error is the router's
                # stale-vs-real discriminator, same as in-process.
                err = self._thread_error
                if err is not None:
                    e = RuntimeError(
                        f"{self.name}: connection lost; request {rid} "
                        f"awaiting failover")
                    e.__cause__ = err
                    raise e
            span = min(remaining, 1.0)
            try:
                out = self._call("wait", rid=int(rid), timeout=span,
                                 reply_timeout=span + 2.0)
            except TransportError:
                time.sleep(0.01)
                continue        # reconnect/backoff loop; re-check state
            except TimeoutError as e:
                # only a PLAIN TimeoutError is "not finished yet" —
                # DeadlineExceeded subclasses it and is a terminal,
                # typed request outcome that must reach the caller
                if type(e) is TimeoutError:
                    continue
                self._settle_all(rid)
                raise
            except Exception:
                self._settle_all(rid)
                raise           # typed failure unmarshalled remotely
            else:
                self._reconcile_stream(rid, out)
                self._settle_all(rid)
                return np.asarray(out, np.int32)

    def _reconcile_stream(self, rid, toks):
        """Terminal backfill: a wire-returned result is the WHOLE
        stream, so any token pushes chaos ate with nothing behind them
        to trigger a ``fetch_tokens`` re-ask are delivered to the
        stream callback here, before the mirror settles — a waited
        request's callback never ends truncated."""
        with self._state_lock:
            m = self._mirror.get(rid)
            if m is None or m.done or m.on_token is None:
                return
            tail = [int(t) for t in toks[len(m.tokens):]]
            if not tail:
                return
            m.tokens.extend(tail)
            cb = m.on_token
        try:
            cb(rid, tail)
        except Exception:
            pass                # a poisoned stream cannot spoil wait()

    def _settle_mirror(self, rid):
        m = self._mirror.pop(rid, None)
        if m is not None:
            m.done = True
            self._journeys.pop(m.tid, None)

    def _settle_all(self, rid):
        """Wire-delivered outcome for ``rid``: drop the mirror AND any
        concurrently stream-settled local copy — a wait that returned
        via the wire while the final token push also settled locally
        must not strand one result array per request."""
        with self._state_lock:
            self._settle_mirror(rid)
            self._results.pop(rid, None)
            self._failures.pop(rid, None)

    def _bound_settled_locked(self):
        """Cap the locally settled maps (a pure-streaming client may
        never ``wait()``; dropped entries are simply re-fetched from
        the host's own delivery stash if a late wait does arrive)."""
        for d in (self._results, self._failures):
            while len(d) > 4096:
                d.pop(next(iter(d)))

    def cancel(self, rid):
        try:
            return bool(self._call("cancel", rid=int(rid)))
        except (TransportError, TimeoutError):
            return False    # unreachable host: failover settles it

    # ------------------------------------------- live KV-page migration
    def _mint_xid(self):
        with self._id_lock:
            xid = f"x{self._next_id}"
            self._next_id += 1
        return xid

    def migrate_out(self, rid, retry=None, partial=False, from_page=0):
        """Pause ``rid`` on the host and pull its full resumable state
        over the wire: the serialized migration dict plus one host
        array per KV page (binary page frames, sha256-checked per
        frame by the transport and end-to-end again by the target's
        ``migrate_in``). Transient failures — a severed call, page
        frames the storm ate — RESUME the slot and retry with backoff;
        a typed host refusal (``MigrationError``: unknown rid, dense
        backend) propagates immediately so the caller degrades to
        evacuate+replay. The client mirror stays registered until
        ``migrate_finish`` commits the handoff.

        ``partial=True`` pulls one NON-pausing pipelined batch of a
        mid-prefill slot's complete pages (single attempt, no resume
        needed — nothing pauses); frames the wire ate come back as
        ``None`` holes in the payload list, so the pump re-ships
        exactly those. ``from_page`` skips pages the target already
        holds on the closing full pull."""
        if partial:
            return self._migrate_out_partial(rid)
        policy = retry if retry is not None else self.migrate_retry
        last = None
        for attempt in range(self.migrate_attempts):
            if attempt:
                policy.sleep(attempt - 1)
            xid = self._mint_xid()
            with self._state_lock:
                self._mig_pages[xid] = {}
            try:
                try:
                    state = self._call("migrate_out", rid=int(rid),
                                       xid=xid,
                                       from_page=int(from_page))
                except MigrationError:
                    raise             # host refusal: not transient
                except (TransportError, TimeoutError) as e:
                    last = e
                    self.migrate_abort(rid)   # resume if it paused
                    continue
                with self._state_lock:
                    got = self._mig_pages.get(xid) or {}
                n = len(state.get("sha256") or ())
                payloads = [got.get(i) for i in range(n)]
                # zero payloads are legitimate for a prefill handoff
                # (nothing written yet) or a closing pull whose pages
                # all streamed ahead (from_page == written extent)
                empty_ok = int(state.get("base") or 0) > 0 \
                    or str(state.get("phase") or "decode") == "prefill"
                if (n == 0 and not empty_ok) \
                        or any(p is None for p in payloads):
                    last = MigrationError(
                        f"{self.name}: request {rid}: page frames lost "
                        f"on the wire ({len(got)}/{n} arrived)")
                    self.migrate_abort(rid)   # slot is paused: resume
                    continue
                # the server fires token callbacks AFTER releasing its
                # tick lock, so a cut landing in that window returns
                # `streamed` ahead of what this wire has seen — the
                # pushes are in flight on a live conn and the slot is
                # paused (`streamed` is final), so wait for the mirror
                # to catch up before snapshotting; a timeout means the
                # push was genuinely lost (dying host) and client truth
                # stands — the target re-streams the gap
                srv_streamed = int(state.get("streamed") or 0)
                catchup = time.monotonic() + 2.0
                while time.monotonic() < catchup:
                    with self._state_lock:
                        m = self._mirror.get(rid)
                        if m is None or len(m.tokens) >= srv_streamed:
                            break
                    time.sleep(0.002)
                with self._state_lock:
                    m = self._mirror.get(rid)
                    if m is not None:
                        # CLIENT-truth delivery offset: the target
                        # seeds its mirror from this, so gap repair
                        # picks up exactly where this wire left off
                        state["delivered"] = [int(t) for t in m.tokens]
                return state, payloads
            finally:
                with self._state_lock:
                    self._mig_pages.pop(xid, None)
        raise last

    def migrate_in(self, state, payloads, on_token=None, journey=None):
        """Restore a migrated request INTO this replica: stream the
        page payloads as binary frames, then commit with the state
        (the reply is the COMMIT POINT — the new remote rid). A mirror
        is registered client-side, seeded with the already-delivered
        token prefix, so dead-host synthesis and gap repair keep
        working across the handoff. Any failure propagates — the
        caller aborts the source and falls back."""
        conn = self._ensure_conn()
        xid = self._mint_xid()
        for i, p in enumerate(payloads):
            a = np.ascontiguousarray(np.stack(p) if isinstance(p, list)
                                     else p)
            conn.send_pages({"id": 0, "op": "migrate_page", "xid": xid,
                             "i": i, "n": len(payloads),
                             "shape": list(a.shape),
                             "dtype": str(a.dtype)}, a.tobytes())
        tid = getattr(journey, "tid", None)
        where = getattr(journey, "where", None)
        if tid is not None:
            self._journeys[tid] = journey
        streamed = int(state.get("streamed") or 0)
        pre = state.get("delivered")
        if pre is None:
            # in-process sources stream synchronously: server-truth
            # offset IS client truth there
            pre = (state.get("emitted") or [])[:streamed]
        pre = [int(t) for t in pre]
        deadline = None if state.get("deadline_s") is None \
            else self._clock.now() + float(state["deadline_s"])

        def record(reply):
            with self._state_lock:
                m = _Mirror(reply["rid"],
                            np.asarray(state["ids"], np.int32),
                            int(state["budget"]), int(state["seed"]),
                            on_token, deadline,
                            int(state.get("priority") or 0),
                            journey, tid)
                m.tokens = list(pre)
                self._mirror[reply["rid"]] = m
                parked = self._early_tokens.pop(reply["rid"], ())
            for pm in parked:         # pushes that raced this reply
                self._on_tokens(pm)

        try:
            reply = self._call("migrate_in", xid=xid,
                               state=jsonable(state), tid=tid,
                               where=where, on_reply=record)
        except BaseException:
            if tid is not None:
                self._journeys.pop(tid, None)
            raise
        return reply["rid"]

    def _migrate_out_partial(self, rid):
        """One pipelined batch pull (``migrate_out(partial=True)``):
        single attempt — the slot never pauses, so there is nothing to
        resume and the next poll simply re-reads progress. Lost frames
        come back as ``None`` holes; the pump re-ships them through
        the closing ``from_page`` pull."""
        xid = self._mint_xid()
        with self._state_lock:
            self._mig_pages[xid] = {}
        try:
            frag = self._call("migrate_out", rid=int(rid), xid=xid,
                              partial=True)
            with self._state_lock:
                got = self._mig_pages.get(xid) or {}
            n = len(frag.get("sha256") or ())
            return frag, [got.get(i) for i in range(n)]
        finally:
            with self._state_lock:
                self._mig_pages.pop(xid, None)

    def migrate_in_begin(self, state):
        """Open a pipelined restore on the host (disaggregated prefill
        handoff target): returns the transfer handle the page batches
        and the commit key off. Any failure propagates — the caller
        falls back to a one-shot ``migrate_in`` or local decode."""
        return int(self._call("migrate_in_begin",
                              state=jsonable(state))["handle"])

    def migrate_in_pages(self, handle, base, payloads, sha256=None):
        """Ship one pipelined page batch as binary frames and scatter
        it at page index ``base`` of the staged restore. Returns the
        list of ABSOLUTE page indices that actually landed (the wire
        may eat frames mid-storm; the pump re-ships the difference) —
        the in-process server returns a bare count instead, so pumps
        normalize on both."""
        conn = self._ensure_conn()
        xid = self._mint_xid()
        sha = list(sha256 or ())
        for i, p in enumerate(payloads):
            a = np.ascontiguousarray(np.stack(p) if isinstance(p, list)
                                     else p)
            conn.send_pages({"id": 0, "op": "migrate_page", "xid": xid,
                             "i": i, "n": len(payloads),
                             "shape": list(a.shape),
                             "dtype": str(a.dtype)}, a.tobytes())
        r = self._call("migrate_in_pages", handle=int(handle),
                       xid=xid, base=int(base), sha256=sha)
        return [int(i) for i in r.get("landed") or ()]

    def migrate_in_commit(self, handle, state, payloads=(),
                          on_token=None, journey=None):
        """Close a pipelined restore: stream the closing batch, commit
        with the full state (the reply is the COMMIT POINT — the new
        remote rid), and register the client mirror exactly like
        ``migrate_in`` so dead-host synthesis and gap repair keep
        working across the handoff."""
        conn = self._ensure_conn()
        xid = self._mint_xid()
        for i, p in enumerate(payloads):
            a = np.ascontiguousarray(np.stack(p) if isinstance(p, list)
                                     else p)
            conn.send_pages({"id": 0, "op": "migrate_page", "xid": xid,
                             "i": i, "n": len(payloads),
                             "shape": list(a.shape),
                             "dtype": str(a.dtype)}, a.tobytes())
        tid = getattr(journey, "tid", None)
        where = getattr(journey, "where", None)
        if tid is not None:
            self._journeys[tid] = journey
        streamed = int(state.get("streamed") or 0)
        pre = state.get("delivered")
        if pre is None:
            pre = (state.get("emitted") or [])[:streamed]
        pre = [int(t) for t in pre]
        deadline = None if state.get("deadline_s") is None \
            else self._clock.now() + float(state["deadline_s"])

        def record(reply):
            with self._state_lock:
                m = _Mirror(reply["rid"],
                            np.asarray(state["ids"], np.int32),
                            int(state["budget"]), int(state["seed"]),
                            on_token, deadline,
                            int(state.get("priority") or 0),
                            journey, tid)
                m.tokens = list(pre)
                self._mirror[reply["rid"]] = m
                parked = self._early_tokens.pop(reply["rid"], ())
            for pm in parked:         # pushes that raced this reply
                self._on_tokens(pm)

        try:
            reply = self._call("migrate_in_commit", handle=int(handle),
                               xid=xid, state=jsonable(state), tid=tid,
                               where=where, on_reply=record)
        except BaseException:
            if tid is not None:
                self._journeys.pop(tid, None)
            raise
        return reply["rid"]

    def migrate_in_abort(self, handle):
        """Tear down a staged restore that will never commit
        (best-effort, idempotent — an unreachable host's staging dies
        with the process)."""
        try:
            return bool(self._call("migrate_in_abort",
                                   handle=int(handle)))
        except (TransportError, TimeoutError):
            return False

    def migrate_finish(self, rid):
        """Settle a committed handoff on the source: drop the local
        mirror FIRST — a post-commit host crash must not let dead-wire
        evacuate synthesis double-deliver a request that now lives on
        the target — then release the host's paused slot best-effort
        (an unreachable host's slot dies with the process anyway)."""
        with self._state_lock:
            m = self._mirror.pop(rid, None)
            if m is not None:
                m.done = True
                self._journeys.pop(m.tid, None)
        try:
            self._call("migrate_finish", rid=int(rid))
            return True
        except (TransportError, TimeoutError, MigrationError):
            return False

    def migrate_abort(self, rid):
        """Resume a paused migration source slot (best-effort: an
        unreachable host has nothing usefully paused — the failover
        path settles the request from the mirror)."""
        try:
            return bool(self._call("migrate_abort", rid=int(rid)))
        except (TransportError, TimeoutError):
            return False

    # --------------------------------------------------- router surface
    def _wire_dead(self):
        conn = self._conn
        return conn is None or conn.closed

    @property
    def health(self):
        """Digest health bounded by staleness: a silent host walks
        ``draining`` -> ``dead`` as heartbeats go missing; a severed
        connection reads ``dead`` immediately."""
        if self._closed or self._wire_dead() or self._digest is None:
            return DEAD
        age = self._clock.now() - self._last_hb
        if age >= self.dead_after_s:
            return DEAD
        if age >= self.draining_after_s:
            return DRAINING
        return self._digest.get("health", DEAD)

    @property
    def role(self):
        """Placement role from the last heartbeat digest. Pre-ISSUE-20
        hosts never send the key and read as ``"hybrid"`` — a
        mixed-version fleet routes safely instead of KeyError'ing in
        the placement scan."""
        role = (self._digest or {}).get("role")
        return role if role in ("prefill", "decode", "hybrid") \
            else "hybrid"

    def _mirror_counts(self):
        # LOCK-FREE routing read (the router calls this per submit for
        # every replica): list(dict.values()) is one atomic C-level
        # snapshot under the GIL, so no _state_lock is taken and the
        # reader thread's token pushes are never contended with. The
        # mirror holds at most queue + slots live entries, so the scan
        # is short.
        q = f = 0
        for m in list(self._mirror.values()):
            if m.done:
                continue
            if m.tokens:
                f += 1
            else:
                q += 1
        return q, f

    def queue_depth(self):
        """The router's load read. Live wire: the last pushed digest
        FLOORED by the client mirror — a burst of submits inside one
        heartbeat must weigh on the routing score immediately, not
        after the next digest lands (the digest alone made a freshly
        loaded remote look idle to least-loaded). Dead wire: the
        mirror alone — a stale digest can no longer tell the
        supervisor whether a sweep is owed."""
        if self._wire_dead():
            return self._mirror_counts()[0]
        return max(int((self._digest or {}).get("queue_depth", 0)),
                   self._mirror_counts()[0])

    def in_flight(self):
        if self._wire_dead():
            return self._mirror_counts()[1]
        return max(int((self._digest or {}).get("in_flight", 0)),
                   self._mirror_counts()[1])

    def preempt_pressure(self):
        if self._wire_dead():
            return 0
        return int((self._digest or {}).get("preempt_pressure", 0))

    def prefix_sketch(self):
        return self._sketch

    def utilization(self):
        """The replica's goodput ratio + MFU from its last heartbeat
        digest (lock-free attribute read, same staleness contract as
        the other routing reads) — ``{}`` when the remote server wires
        neither a goodput ledger nor a cost catalog, or the wire is
        dead (a corpse reports no utilization)."""
        if self._wire_dead():
            return {}
        return dict((self._digest or {}).get("util") or {})

    @property
    def stats(self):
        return dict((self._digest or {}).get("stats") or {})

    def evacuate(self, flush_partials=False):
        """Harvest this replica's queue for the router. With a live
        wire this is the host's own ``evacuate`` (deadlines come back
        as remaining seconds and re-anchor here). With the wire DEAD it
        is synthesized from the mirror: requests that streamed nothing
        are harvested for bit-exact requeue, requests caught mid-decode
        flush their streamed partial to the waiter (the in-process
        ``flush_partials`` split, reconstructed from this side of the
        wire)."""
        if not self._wire_dead():
            entries = self._call("evacuate",
                                 flush_partials=bool(flush_partials))
            now = self._clock.now()
            out = []
            with self._state_lock:
                for e in entries:
                    m = self._mirror.pop(e["rid"], None)
                    if m is not None:
                        m.done = True
                        self._journeys.pop(m.tid, None)
                    out.append(_Harvested(
                        e["rid"], np.asarray(e["ids"], np.int32),
                        e["budget"], e["seed"],
                        m.on_token if m is not None else None,
                        None if e.get("deadline_s") is None
                        else now + float(e["deadline_s"]),
                        e.get("priority") or 0,
                        m.journey if m is not None else None))
            return out
        out = []
        with self._state_lock:
            for rid, m in list(self._mirror.items()):
                if m.done:
                    continue
                self._mirror.pop(rid)
                m.done = True
                self._journeys.pop(m.tid, None)
                if m.tokens:
                    # mid-decode on the corpse: replaying elsewhere
                    # would double-stream — the partial is the result
                    self._results[rid] = np.asarray(
                        m.tokens[:m.budget], np.int32)
                    if m.journey is not None:
                        m.journey.event("flushed",
                                        tokens=len(self._results[rid]),
                                        synthesized=True)
                else:
                    out.append(_Harvested(rid, m.ids, m.budget, m.seed,
                                          m.on_token, m.deadline,
                                          m.priority, m.journey))
        return out

    def abandon(self, rid, err):
        try:
            return bool(self._call("abandon", rid=int(rid),
                                   err=marshal_error(err)))
        except (TransportError, TimeoutError):
            return False

    def postmortems(self):
        try:
            return self._call("postmortems") or []
        except (TransportError, TimeoutError):
            return []

    def pool_balance(self):
        """The remote pool's ``(free, live, pinned, cached)`` balance
        (None for a dense backend or an unreachable host) — the chaos
        suites' zero-leak probe, over the wire."""
        try:
            b = self._call("pool_balance")
        except (TransportError, TimeoutError):
            return None
        if b is None:
            return None
        from .continuous_batching import PoolBalance
        return PoolBalance(b["free"], b["live"], b["pinned"],
                           b["cached"], preempted=b["preempted"],
                           preemptions=b["preemptions"])

    def registry_snapshot(self):
        """The remote server's metric-registry snapshot (decoded to the
        local snapshot shape), or None — ``fleet_snapshot()`` merges it
        so ``/fleet`` spans process boundaries. Bounded by a SHORT
        reply timeout (`snapshot_timeout_s`, default 2 s), not the
        general call budget: a wedged host must cost a scrape one
        missing contributor, not a 30 s stall of the metrics server."""
        try:
            snap = self._call("snapshot",
                              reply_timeout=self.snapshot_timeout_s)
        except (TransportError, TimeoutError):
            return None
        return None if snap is None else decode_snapshot(snap)

    # --------------------------------------------------------- lifecycle
    def start(self):
        self._call("start")
        self._thread = "remote-serve"
        return self

    def stop(self, timeout=60.0, drain=False):
        try:
            self._call("stop", drain=bool(drain), timeout=timeout,
                       reply_timeout=float(timeout) + 5.0)
        except (TransportError, TimeoutError):
            if not self._wire_dead():
                raise       # host reachable but the stop itself failed
        self._thread = None

    def kill(self, timeout=60.0):
        """The POLITE kill (wire op): the remote server stops with its
        state intact, process alive — drills that need a real crash
        SIGKILL the spawned process instead."""
        try:
            self._call("kill", timeout=timeout,
                       reply_timeout=float(timeout) + 5.0)
        except (TransportError, TimeoutError):
            if not self._wire_dead():
                raise
        self._thread = None

    def shutdown(self):
        """Ask the host process to exit (reply first, then close), and
        close this client."""
        try:
            self._call("shutdown", reply_timeout=5.0)
        except (TransportError, TimeoutError):
            pass        # already gone: shutdown is idempotent
        self.close()

    def close(self):
        """Client-side teardown only (the host keeps serving others)."""
        self._closed = True
        with self._conn_lock:
            conn, self._conn = self._conn, None
        if conn is not None:
            conn.close()

    def __repr__(self):
        return (f"RemoteReplica({self.name}, health={self.health!r}, "
                f"mirrored={len(self._mirror)})")


# ------------------------------------------------------ process spawning
def _host_main(factory, factory_kwargs, pipe, host, heartbeat_s,
               start_server):
    """Child-process entry point: build the server from the picklable
    factory, serve it, report the bound port, park until shutdown."""
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    server = factory(**(factory_kwargs or {}))
    h = ReplicaHost(server, host=host, port=0,
                    heartbeat_s=heartbeat_s).start()
    if start_server:
        server.start()
    pipe.send(h.port)
    pipe.close()
    h.wait_shutdown()


def spawn_replica_host(factory, factory_kwargs=None, host="127.0.0.1",
                       heartbeat_s=0.02, method="spawn",
                       start_server=False, startup_timeout=120.0):
    """Spawn a replica host in its OWN process: ``factory(**kwargs)``
    (a module-level, picklable callable) builds the
    ``ContinuousBatchingServer`` in the child. Returns
    ``(process, address)`` once the child reports its port — connect a
    ``RemoteReplica`` to ``address``, SIGKILL ``process`` to crash it
    for real. ``method`` is the multiprocessing start method
    (``"spawn"`` pays a fresh interpreter but never inherits jax
    runtime state mid-flight)."""
    import multiprocessing as mp
    ctx = mp.get_context(method)
    parent, child = ctx.Pipe()
    proc = ctx.Process(target=_host_main,
                       args=(factory, factory_kwargs, child, host,
                             heartbeat_s, start_server),
                       daemon=True)
    proc.start()
    child.close()
    try:
        if not parent.poll(startup_timeout):
            raise TransportError(
                f"replica host did not report a port within "
                f"{startup_timeout}s")
        port = parent.recv()
    except (TransportError, EOFError, OSError) as e:
        proc.kill()
        proc.join(5.0)
        err = TransportError(
            f"replica host child died before reporting a port "
            f"(exitcode={proc.exitcode})")
        if not isinstance(e, TransportError):
            err.__cause__ = e
        raise err
    finally:
        parent.close()
    return proc, (host, int(port))
