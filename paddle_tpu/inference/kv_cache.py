"""Paged KV-cache bookkeeping for the continuous-batching server.

The dense decode backend allocates ``[max_slots, ..., max_cache_len]``
KV buffers, so cache HBM scales with the CONFIGURED cache length. The
paged backend (cf. "Ragged Paged Attention", PAPERS.md) stores K/V in a
fixed global pool ``[num_pages, page_size, kv_heads, head_dim]`` per
layer and gives each slot an ordered block table of page ids — HBM and
decode bandwidth then scale with ACTUAL tokens, and a pool sized to the
real working set serves slot counts x cache lengths that a dense layout
could not.

This module is the HOST-side allocator: free-list page alloc/release on
slot admit/harvest, per-slot block tables (the device copy is refreshed
only when rows change — no recompiles, the table is a runtime argument
of the decode program), and refcounted page sharing so a registered
prompt prefix is stored ONCE and referenced by every slot that starts
with it. Page 0 is reserved as a null page: unused block-table entries
point at it (gathers through them are length-masked) and inactive slots'
wasted decode writes are redirected to it, so a stale write can never
corrupt a live slot's pages.
"""
import numpy as np

from ..reliability.faults import KV_GROW, PAGE_ALLOC

__all__ = ["PagedKVCache", "OutOfPages", "NULL_PAGE"]

NULL_PAGE = 0


class OutOfPages(RuntimeError):
    """The page pool cannot satisfy an allocation. At admission this
    just defers the request (it stays queued until a slot frees pages);
    mid-decode it is surfaced — size ``num_pages`` to the worst-case
    working set (sum over concurrent slots of ceil(len / page_size))."""


class PagedKVCache:
    """Free-list page allocator + per-slot block tables.

    ``block_table`` is the ``[max_slots, pages_per_slot]`` int32 host
    mirror handed to the decode program (rows are page ids in position
    order; unused entries hold ``NULL_PAGE``). ``dirty`` flags that the
    device copy needs a refresh.
    """

    def __init__(self, num_pages, page_size, max_slots, pages_per_slot,
                 fault_injector=None):
        if page_size < 1 or pages_per_slot < 1:
            raise ValueError("page_size and pages_per_slot must be >= 1")
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is the "
                             "reserved null page)")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.max_slots = int(max_slots)
        self.pages_per_slot = int(pages_per_slot)
        self.block_table = np.zeros((max_slots, pages_per_slot), np.int32)
        self._free = list(range(num_pages - 1, 0, -1))  # pop() -> low ids
        self._ref = np.zeros((num_pages,), np.int32)
        self._slot_pages = [[] for _ in range(max_slots)]
        self._slot_shared = [0] * max_slots
        self.dirty = True
        # chaos hook (reliability.FaultInjector): alloc() checks the
        # "kv.alloc" point BEFORE touching the free list, so an injected
        # allocation failure can never leak pages
        self._faults = fault_injector
        # last-resort page source: when the free list runs short,
        # ``alloc`` calls ``reclaimer(shortfall)`` once before giving
        # up — the prefix cache hooks in here to evict LRU cached
        # pages. The callback must release pages (growing the free
        # list) and MUST NOT raise; it returns the count it freed.
        self.reclaimer = None
        # cumulative churn counters (telemetry: page-pool pressure and
        # sharing effectiveness without polling mid-operation)
        self.alloc_total = 0       # pages taken off the free list
        self.freed_total = 0       # pages returned (refcount hit 0)
        self.shared_ref_total = 0  # extra refs taken on shared pages
        self.grown_total = 0       # pages appended mid-decode (grow_slot)

    # ------------------------------------------------------- allocation
    def _npages(self, n_tokens):
        return -(-int(n_tokens) // self.page_size)

    def free_pages(self):
        return len(self._free)

    def used_pages(self):
        return self.num_pages - 1 - len(self._free)

    def alloc(self, n):
        """Take ``n`` pages off the free list (refcount 1 each). A
        short free list first asks ``reclaimer`` (the prefix cache's
        LRU eviction) to make up the difference."""
        if self._faults is not None:
            self._faults.check(PAGE_ALLOC, need=n)
        if n > len(self._free) and self.reclaimer is not None:
            self.reclaimer(n - len(self._free))
        if n > len(self._free):
            raise OutOfPages(
                f"need {n} pages but only {len(self._free)} of "
                f"{self.num_pages - 1} are free — grow num_pages or "
                f"admit fewer concurrent slots")
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._ref[p] = 1
        self.alloc_total += n
        return pages

    def release(self, pages):
        """Drop one reference per page; pages reaching zero return to
        the free list (slot teardown, or rolling back an alloc)."""
        for p in pages:
            self._ref[p] -= 1
            if self._ref[p] == 0:
                self._free.append(p)
                self.freed_total += 1

    def refcount(self, page):
        """Live references on ``page`` (prefix-cache eviction treats
        anything above the tree's own 1 as in-use)."""
        return int(self._ref[page])

    # ------------------------------------------------------- slot state
    def coverage(self, slot):
        """Tokens the slot's current pages can hold."""
        return len(self._slot_pages[slot]) * self.page_size

    def slot_pages(self, slot):
        return list(self._slot_pages[slot])

    def admit_slot(self, slot, n_tokens, shared_pages=()):
        """Give ``slot`` a block table covering ``n_tokens`` positions —
        the request's FULL extent (prompt + budget), reserved up front
        so decode can never hit an empty pool mid-flight:
        ``shared_pages`` (refcounted, e.g. a registered prefix's full
        pages) cover the head, fresh pages the rest. Returns the fresh
        page ids — the caller copies the slot's own KV rows (positions
        ``len(shared_pages) * page_size`` onward) into them."""
        if self._slot_pages[slot]:
            raise RuntimeError(f"slot {slot} already holds pages")
        need = self._npages(n_tokens)
        need = max(need, len(shared_pages))
        if need > self.pages_per_slot:
            raise ValueError(
                f"{n_tokens} tokens need {need} pages > pages_per_slot "
                f"({self.pages_per_slot})")
        # reference the shared pages BEFORE allocating: alloc may evict
        # via the reclaimer, and a cached page this slot is about to
        # reuse must already read as in-use (refcount > 1) or the sweep
        # could free-and-recycle it mid-admission
        for p in shared_pages:
            self._ref[p] += 1
        self.shared_ref_total += len(shared_pages)
        try:
            own = self.alloc(need - len(shared_pages))
        except Exception:
            for p in shared_pages:
                self._ref[p] -= 1
            self.shared_ref_total -= len(shared_pages)
            raise
        pages = list(shared_pages) + own
        self._slot_pages[slot] = pages
        self._slot_shared[slot] = len(shared_pages)
        row = self.block_table[slot]
        row[:] = NULL_PAGE
        row[:len(pages)] = pages
        self.dirty = True
        return own

    def grow_slot(self, slot, n):
        """Append ``n`` fresh pages to a live slot's block table —
        optimistic admission grows a slot page-by-page as decode
        crosses page boundaries instead of reserving its full extent
        up front. The ``kv.grow`` chaos point fires BEFORE the free
        list is touched, so an injected grow failure is a clean
        transient (nothing to roll back). Raises ``OutOfPages`` when
        the pool (plus whatever the reclaimer can evict) cannot supply
        the pages — the server's preemption policy then frees a
        victim's pages and retries. Returns the new page ids."""
        if self._faults is not None:
            self._faults.check(KV_GROW, slot=slot, need=n)
        pages = self._slot_pages[slot]
        if not pages:
            raise RuntimeError(f"slot {slot} holds no pages to grow")
        if len(pages) + n > self.pages_per_slot:
            raise ValueError(
                f"growing slot {slot} by {n} pages exceeds "
                f"pages_per_slot ({self.pages_per_slot})")
        own = self.alloc(n)
        row = self.block_table[slot]
        row[len(pages):len(pages) + n] = own
        pages.extend(own)
        self.dirty = True
        self.grown_total += n
        return own

    def free_slot(self, slot):
        """Release the slot's pages (shared pages just drop a ref) and
        null its block-table row so stale decode writes are redirected
        to the null page."""
        self.release(self.detach_slot(slot))

    def detach_slot(self, slot):
        """Hand the slot's pages to the caller WITHOUT dropping any
        references — prefix-cache donation takes over their ownership —
        and null the block-table row like ``free_slot``."""
        pages = self._slot_pages[slot]
        self._slot_pages[slot] = []
        self._slot_shared[slot] = 0
        self.block_table[slot, :] = NULL_PAGE
        self.dirty = True
        return pages

    # ------------------------------------------------------- accounting
    def occupancy(self, num_shards=1, host_tier=None):
        """Per-slot block-table occupancy, plain data — the postmortem
        bundle's "who holds which pages" section: pages held and
        shared-prefix pages per occupied slot, plus the pool totals.

        With ``num_shards > 1`` (kv-head-sharded pool on a mesh) a
        ``shards`` view is appended.  The allocator is host-side and
        global — every page id exists on every shard, split on the
        kv-head dim — so per-shard occupancy equals the global counts
        on each shard; the view states that balance explicitly so
        dashboards and postmortems assert it instead of assuming it.

        ``host_tier`` (a ``kv_tier.HostTier.stats()`` dict, or the
        tier itself) appends the host tier's residency as a
        ``host_tier`` section — the "where did the evicted pages GO"
        half of the occupancy picture once spill-to-host is on."""
        occ = {"free_pages": self.free_pages(),
               "used_pages": self.used_pages(),
               "pages_per_slot": self.pages_per_slot,
               "slots": {s: {"pages": len(p),
                             "shared": self._slot_shared[s]}
                         for s, p in enumerate(self._slot_pages) if p}}
        if num_shards > 1:
            occ["shards"] = [{"shard": i,
                              "free_pages": occ["free_pages"],
                              "used_pages": occ["used_pages"]}
                             for i in range(num_shards)]
        if host_tier is not None:
            occ["host_tier"] = dict(host_tier.stats()
                                    if hasattr(host_tier, "stats")
                                    else host_tier)
        return occ

    def telemetry_stats(self):
        """Point-in-time pool state + cumulative churn, plain data —
        the ``/stats`` payload and the page-pool gauges source."""
        return {"num_pages": self.num_pages,
                "page_size": self.page_size,
                "free_pages": self.free_pages(),
                "used_pages": self.used_pages(),
                "alloc_total": self.alloc_total,
                "freed_total": self.freed_total,
                "shared_ref_total": self.shared_ref_total,
                "grown_total": self.grown_total}

    @staticmethod
    def paged_hbm_bytes(num_pages, page_size, layers, kv_heads, head_dim,
                        itemsize=4):
        """K+V pool bytes for a paged cache config."""
        return 2 * layers * num_pages * page_size * kv_heads * head_dim \
            * itemsize

    @staticmethod
    def dense_hbm_bytes(max_slots, max_cache_len, layers, kv_heads,
                        head_dim, itemsize=4):
        """K+V bytes the dense backend allocates for the same serving
        config — the baseline the paged pool is measured against."""
        return 2 * layers * max_slots * max_cache_len * kv_heads \
            * head_dim * itemsize
