"""Typed length-prefixed RPC framing for the process-isolated fleet
(ISSUE 12; the reference stack's L5 ProcessGroup + TCPStore shape —
a thin typed control plane over TCP, not a framework).

Every robustness guarantee the router advertises was tested inside ONE
process until now: replicas were objects, a "crash" was a method call,
and a partition could not happen. This module is the wire those
guarantees now have to cross:

Frame layout (the whole protocol)::

    +----------------+----------------------------------------+
    | length: 4 bytes| payload: <length> bytes of UTF-8 JSON  |
    | big-endian u32 | (one JSON object per frame)            |
    +----------------+----------------------------------------+

One frame is one message. A length above the connection's frame cap
(``max_frame_bytes``, default ``MAX_FRAME``) or a stream that ends
mid-frame means the byte stream can no longer be trusted and the
connection is closed; a payload that is not a JSON object spoils only
ITSELF — framing stayed in sync, so the receiver drops the frame and
keeps serving (the frame-corruption fuzz suite pins both behaviours).

BINARY page frames (ISSUE 18, live KV-page migration): float arrays
must not round-trip through JSON, so ``send_pages(header, payload)``
emits one ordinary JSON header frame — the caller's dict plus
``_bin`` (raw byte count) and ``_sha256`` (payload digest) — followed
by exactly ``_bin`` raw bytes on the same stream::

    +--------+---------------------+---------------------------+
    | length | header JSON (+_bin, | raw payload: <_bin> bytes |
    | u32    |  +_sha256)          | (page bytes, no encoding) |
    +--------+---------------------+---------------------------+

``recv`` reads the payload unconditionally (any consumer keeps the
stream in sync) and verifies the digest: a mismatch raises
``FrameError`` AFTER the bytes were consumed — only that transfer is
spoiled, the connection keeps serving, and the migration layer above
degrades to replay. An oversized payload fails typed (``FrameError``)
BEFORE anything hits the wire; senders chunk page groups under the
cap instead.

Typed errors cross the wire by NAME: ``marshal_error`` flattens any
exception to ``{"kind", "message"}`` and ``unmarshal_error`` rebuilds
the matching ``reliability.ReliabilityError`` subclass (or builtin
exception) on the caller's side, so a remote ``DeadlineExceeded`` is
still a ``DeadlineExceeded`` to the client that branches on type.

Chaos (reliability.faults): ``Connection`` checks ``net.send`` /
``net.recv`` on every frame and ``net.partition`` on both directions.
The armed error CLASS picks the failure mode — ``NetDrop`` (the frame
vanishes; the sender believes it was sent, the receiver never sees
it), ``NetDelay`` (late delivery), ``NetTruncate`` (a partial frame,
then a hard close — the peer sees a corrupt stream), ``NetSever`` or
a plain ``InjectedFault`` (connection cut). Fires draw from the same
seeded per-point PRNG streams as every other chaos point, so a
partition storm replays exactly.

Everything here is stdlib-only and import-light: a spawned replica
host must be able to load the wire layer before it pays for jax.
"""
import builtins
import hashlib
import json
import select
import socket
import struct
import threading
import time

from ..reliability import errors as _errors
from ..reliability import faults
from ..reliability.errors import (FrameError, InjectedFault,
                                  ReliabilityError, TransportError)

__all__ = ["Connection", "connect", "MAX_FRAME", "NetDrop", "NetDelay",
           "NetTruncate", "NetSever", "marshal_error", "unmarshal_error",
           "encode_snapshot", "decode_snapshot", "jsonable"]

# one frame must hold a full registry snapshot or postmortem bundle,
# never an attacker-sized allocation: past this the stream is closed.
# The DEFAULT cap — a Connection carrying big page groups raises its
# own ``max_frame_bytes`` instead of loosening every peer's guard.
MAX_FRAME = 8 * 1024 * 1024
_LEN = struct.Struct("!I")


# --------------------------------------------------------- chaos modes
class NetDrop(InjectedFault):
    """The frame vanishes in flight: a send returns as if delivered, a
    recv consumes and discards one inbound frame. The affected CALL
    times out at its deadline — the connection survives."""


class NetDelay(InjectedFault):
    """The frame is delivered late (``SECONDS``). Models congestion:
    deadlines keep charging while the wire dawdles."""

    SECONDS = 0.02


class NetTruncate(InjectedFault):
    """Only a prefix of the frame reaches the wire, then the socket
    hard-closes: the peer observes a mid-frame EOF (stream desync) and
    tears the connection down."""


class NetSever(InjectedFault):
    """The connection is cut outright — also the effect of a plain
    ``InjectedFault`` at any ``net.*`` point, and of ``net.partition``
    whichever direction traffic was flowing."""


# ----------------------------------------------------- error marshalling
def marshal_error(exc):
    """Flatten ``exc`` to a wire dict: ``{"kind": type name,
    "message": str}``. The TYPE is the contract (clients branch on the
    ``ReliabilityError`` family), the message is for humans."""
    return {"kind": type(exc).__name__, "message": str(exc)}


def unmarshal_error(d):
    """Rebuild a marshalled error as the most faithful local type: the
    named ``reliability.errors`` class when it exists (the whole typed
    family crosses the wire), a builtin exception otherwise
    (``TimeoutError``, ``ValueError``, ...), else a ``RuntimeError``
    tagged with the foreign kind — never a silent downgrade to str."""
    kind = str(d.get("kind", "RuntimeError"))
    msg = str(d.get("message", ""))
    cls = getattr(_errors, kind, None)
    if isinstance(cls, type) and issubclass(cls, ReliabilityError):
        try:
            err = cls(msg)
        except Exception:
            # a family member whose constructor cannot rebuild from a
            # bare message (CallbackError's error list) degrades to
            # the typed BASE, keeping the family contract for catchers
            return ReliabilityError(f"{kind}: {msg}")
        if isinstance(err, _errors.CallbackError):
            # a short message can unpack as a bogus (rid, error) pair;
            # never hand that half-built object to a caller
            return ReliabilityError(f"{kind}: {msg}")
        return err
    cls = getattr(builtins, kind, None)
    if isinstance(cls, type) and issubclass(cls, Exception):
        try:
            return cls(msg)
        except TypeError:
            pass
    return RuntimeError(f"remote {kind}: {msg}")


# ------------------------------------------------------- JSON adapters
def jsonable(x):
    """Best-effort conversion of host-side structures (numpy scalars /
    arrays, tuples, frozensets, postmortem bundles) into plain JSON
    values. Unknown objects degrade to ``repr`` — a debug payload must
    cross the wire lossy rather than fail the call that carries it."""
    if x is None or isinstance(x, (bool, int, float, str)):
        return x
    if isinstance(x, dict):
        return {str(k): jsonable(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [jsonable(v) for v in x]
    if isinstance(x, (set, frozenset)):
        # mixed-type sets do not order; repr-keyed sort keeps the
        # degrade-lossy promise instead of raising out of a digest
        return sorted((jsonable(v) for v in x), key=repr)
    item = getattr(x, "item", None)
    if callable(item) and getattr(x, "ndim", None) == 0:
        return x.item()                      # numpy scalar
    tolist = getattr(x, "tolist", None)
    if callable(tolist):
        return tolist()                      # numpy array
    return repr(x)


def encode_snapshot(snap):
    """A ``MetricRegistry.snapshot()`` re-keyed for JSON transit: the
    tuple-keyed ``samples`` maps become ``[[key...], value]`` pairs.
    ``decode_snapshot`` is the exact inverse, so a remote replica's
    snapshot merges into ``fleet_snapshot()`` like a local one."""
    out = {}
    for name, m in snap.items():
        out[name] = {"kind": m["kind"], "help": m["help"],
                     "labelnames": list(m["labelnames"]),
                     "samples": [[list(k), _encode_sample(v)]
                                 for k, v in m["samples"].items()]}
    return out


def _encode_sample(v):
    if isinstance(v, dict):                  # histogram child
        return {"buckets": [[le, c] for le, c in v["buckets"]],
                "sum": v["sum"], "count": v["count"]}
    return v


def decode_snapshot(snap):
    """Inverse of ``encode_snapshot`` (returns the registry-snapshot
    shape ``merge_snapshots`` consumes)."""
    out = {}
    for name, m in snap.items():
        samples = {}
        for key, v in m["samples"]:
            if isinstance(v, dict):
                v = {"buckets": [(le, c) for le, c in v["buckets"]],
                     "sum": v["sum"], "count": v["count"]}
            samples[tuple(key)] = v
        out[name] = {"kind": m["kind"], "help": m["help"],
                     "labelnames": tuple(m["labelnames"]),
                     "samples": samples}
    return out


# ---------------------------------------------------------- connection
class Connection:
    """One framed, chaos-instrumented TCP connection.

    ``send(obj)`` frames one JSON object (thread-safe; returns False
    when an injected ``NetDrop`` swallowed the frame). ``recv(timeout)``
    returns the next inbound object, raising ``TimeoutError`` when
    nothing arrives in time, ``FrameError`` for a corrupt-but-resynced
    frame (the caller may keep reading), and ``TransportError`` once
    the connection is unusable (EOF, desync, sever). ``close()`` is
    idempotent and safe from any thread.

    ``registry`` (``telemetry.MetricRegistry``) publishes
    ``net_frames_total{dir}`` / ``net_bytes_total{dir}`` /
    ``net_transport_errors_total``; with the default None the hot path
    pays one ``is None`` check per frame.

    ``max_frame_bytes`` caps BOTH directions and both frame kinds
    (JSON payloads and binary page payloads): an outbound oversize
    fails typed (``FrameError``) before any bytes hit the wire, an
    inbound oversize is a desynced stream. Both peers of a page-
    migrating link must agree on the raised cap.
    """

    def __init__(self, sock, fault_injector=None, registry=None,
                 peer="", max_frame_bytes=MAX_FRAME):
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass    # AF_UNIX (tests' socketpair) has no Nagle to turn off
        self._sock = sock
        self._send_lock = threading.Lock()
        self._rbuf = bytearray()
        self._faults = fault_injector
        self.max_frame_bytes = int(max_frame_bytes)
        self.peer = peer or _peername(sock)
        self.closed = False
        self._c_frames = self._c_bytes = self._c_errors = None
        if registry is not None and getattr(registry, "enabled", False):
            self._c_frames = registry.counter(
                "net_frames_total",
                "Wire frames by direction (sent counts frames that "
                "reached the socket; an injected drop is not sent)",
                labelnames=("dir",))
            self._c_bytes = registry.counter(
                "net_bytes_total", "Wire payload bytes by direction",
                labelnames=("dir",))
            self._c_errors = registry.counter(
                "net_transport_errors_total",
                "Connections torn down by a transport failure "
                "(EOF, frame desync, injected sever)")

    # ------------------------------------------------------------ chaos
    def _chaos(self, point):
        """Run one ``net.*`` check (plus the partition point) and map a
        fire to its wire behaviour. Returns ``"drop"`` when the frame
        must vanish; may sleep (delay), close + raise (truncate /
        sever)."""
        fi = self._faults
        if fi is None:
            return None
        for pt in (faults.NET_PARTITION, point):
            try:
                fi.check(pt, peer=self.peer)
            except NetDrop:
                return "drop"
            except NetDelay as e:
                time.sleep(type(e).SECONDS)
            except NetTruncate as e:
                if point in (faults.NET_SEND, faults.NET_PAGE_SEND):
                    return ("truncate", e)
                self._fail(TransportError(
                    f"injected {pt} truncation severed {self.peer}"), e)
            except InjectedFault as e:      # NetSever or plain fault
                self._fail(TransportError(
                    f"injected {pt} severed connection to "
                    f"{self.peer}"), e)
        return None

    def _fail(self, err, cause=None):
        if self._c_errors is not None:
            self._c_errors.inc()
        self.close()
        if cause is not None:
            err.__cause__ = cause
        raise err

    # ------------------------------------------------------------- send
    def send(self, obj):
        """Frame and send one JSON object. Returns True when the frame
        reached the socket, False when an injected drop swallowed it.
        Raises ``TransportError`` once the connection is unusable."""
        if self.closed:
            raise TransportError(
                f"connection to {self.peer} is closed")
        payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
        if len(payload) > self.max_frame_bytes:
            raise FrameError(
                f"frame of {len(payload)} bytes exceeds max_frame_bytes "
                f"({self.max_frame_bytes}); refusing to desync the "
                f"stream")
        verdict = self._chaos(faults.NET_SEND)
        return self._send_frame(_LEN.pack(len(payload)) + payload,
                                len(payload), verdict, faults.NET_SEND)

    def send_pages(self, header, payload):
        """Frame one BINARY page frame: ``header`` (a JSON-able dict,
        augmented with ``_bin`` = payload byte count and ``_sha256`` =
        payload digest) as an ordinary JSON frame, then the raw
        ``payload`` bytes on the same stream — pool pages cross the
        wire without JSON-encoding float arrays. Chaos point is
        ``net.page_send`` (plus the partition point), so a storm can
        target migration traffic without touching control frames.
        Returns True/False like ``send``; an oversized payload or
        header raises ``FrameError`` BEFORE any bytes hit the wire
        (chunk the page group under ``max_frame_bytes`` instead)."""
        if self.closed:
            raise TransportError(
                f"connection to {self.peer} is closed")
        payload = bytes(payload)
        if len(payload) > self.max_frame_bytes:
            raise FrameError(
                f"binary page frame of {len(payload)} bytes exceeds "
                f"max_frame_bytes ({self.max_frame_bytes}); chunk the "
                f"page group instead of desyncing the stream")
        head = dict(header)
        head["_bin"] = len(payload)
        head["_sha256"] = hashlib.sha256(payload).hexdigest()
        hb = json.dumps(head, separators=(",", ":")).encode("utf-8")
        if len(hb) > self.max_frame_bytes:
            raise FrameError(
                f"page-frame header of {len(hb)} bytes exceeds "
                f"max_frame_bytes ({self.max_frame_bytes})")
        verdict = self._chaos(faults.NET_PAGE_SEND)
        return self._send_frame(_LEN.pack(len(hb)) + hb + payload,
                                len(hb) + len(payload), verdict,
                                faults.NET_PAGE_SEND)

    def _send_frame(self, frame, nbytes, verdict, point):
        """Common tail of send/send_pages: apply the chaos verdict and
        put ``frame`` on the wire."""
        if verdict == "drop":
            return False
        if isinstance(verdict, tuple):      # ("truncate", fault)
            with self._send_lock:
                try:
                    self._sock.sendall(frame[:max(1, len(frame) // 2)])
                except OSError:
                    pass                    # peer already gone: the
                #                             truncation outcome stands
            self._fail(TransportError(
                f"injected {point} truncation severed {self.peer}"),
                verdict[1])
        with self._send_lock:
            try:
                self._sock.sendall(frame)
            except OSError as e:
                self._fail(TransportError(
                    f"send to {self.peer} failed: {e}"), e)
        if self._c_frames is not None:
            self._c_frames.labels(dir="sent").inc()
            self._c_bytes.labels(dir="sent").inc(nbytes)
        return True

    # ------------------------------------------------------------- recv
    def recv(self, timeout=None):
        """Return the next inbound JSON object. ``TimeoutError`` when
        nothing arrives in ``timeout`` seconds; ``FrameError`` for one
        corrupt payload (stream still in sync — keep reading);
        ``TransportError`` when the connection is done for."""
        while True:
            verdict = self._chaos(faults.NET_RECV)
            obj = self._recv_frame(timeout)
            if verdict == "drop":
                continue                    # the frame never "arrived"
            if self._c_frames is not None:
                self._c_frames.labels(dir="recv").inc()
            return obj

    def _recv_frame(self, timeout):
        head = self._read_exact(_LEN.size, timeout)
        (n,) = _LEN.unpack(head)
        if n > self.max_frame_bytes:
            self._fail(TransportError(
                f"inbound frame claims {n} bytes (> max_frame_bytes "
                f"{self.max_frame_bytes}); stream from {self.peer} "
                f"desynced"))
        payload = self._read_exact(n, timeout)
        if self._c_bytes is not None:
            self._c_bytes.labels(dir="recv").inc(n)
        try:
            obj = json.loads(payload.decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            # framing held (we read exactly n bytes) so only THIS
            # frame is spoiled; the connection keeps serving
            raise FrameError(
                f"corrupt {n}-byte frame from {self.peer}: {e}") from e
        nbin = obj.get("_bin") if isinstance(obj, dict) else None
        if nbin is None:
            return obj
        # binary page frame: the raw payload is consumed UNCONDITIONALLY
        # (whoever reads the stream keeps it in sync) and verified here;
        # a digest mismatch spoils only this transfer — framing held, so
        # the connection keeps serving and the migration layer above
        # degrades to replay
        nbin = int(nbin)
        if nbin > self.max_frame_bytes:
            self._fail(TransportError(
                f"binary page frame claims {nbin} payload bytes "
                f"(> max_frame_bytes {self.max_frame_bytes}); stream "
                f"from {self.peer} desynced"))
        blob = self._read_exact(nbin, timeout)
        if self._c_bytes is not None:
            self._c_bytes.labels(dir="recv").inc(nbin)
        if hashlib.sha256(blob).hexdigest() != obj.get("_sha256"):
            raise FrameError(
                f"binary page frame from {self.peer} failed its "
                f"sha256 check ({nbin} bytes)")
        obj["_payload"] = blob
        return obj

    def _read_exact(self, n, timeout):
        buf = self._rbuf
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        while len(buf) < n:
            if self.closed:
                raise TransportError(
                    f"connection to {self.peer} is closed")
            # the recv deadline is waited out in select(), NOT via
            # settimeout: a socket-wide timeout would also govern a
            # concurrent sendall from another thread (send and recv
            # share the fd), turning a slow-draining peer into a
            # spurious connection teardown
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"no frame from {self.peer} in {timeout}s")
                try:
                    ready, _, _ = select.select([self._sock], [], [],
                                                remaining)
                except (OSError, ValueError) as e:
                    # fd closed under us by another thread
                    self._fail(TransportError(
                        f"recv from {self.peer} failed: {e}"), e)
                if not ready:
                    raise TimeoutError(
                        f"no frame from {self.peer} in {timeout}s")
            try:
                chunk = self._sock.recv(65536)
            except socket.timeout:
                raise TimeoutError(
                    f"no frame from {self.peer} in {timeout}s") from None
            except OSError as e:
                self._fail(TransportError(
                    f"recv from {self.peer} failed: {e}"), e)
            if not chunk:
                partial = " mid-frame" if buf or n < _LEN.size else ""
                self._fail(TransportError(
                    f"connection to {self.peer} closed by peer"
                    f"{partial}"))
            buf.extend(chunk)
        out = bytes(buf[:n])
        del buf[:n]
        return out

    # ------------------------------------------------------------ close
    def close(self):
        if self.closed:
            return
        self.closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass        # already reset by the peer / never connected
        try:
            self._sock.close()
        except OSError:
            pass        # double-close race with a failing send/recv


def _peername(sock):
    try:
        host, port = sock.getpeername()[:2]
        return f"{host}:{port}"
    except OSError:
        return "<disconnected>"


def connect(address, timeout=5.0, fault_injector=None, registry=None,
            max_frame_bytes=MAX_FRAME):
    """Dial ``address`` (the ``net.connect`` chaos point) and return a
    ``Connection``. A fired fault or OS-level refusal raises
    ``TransportError``."""
    if fault_injector is not None:
        for pt in (faults.NET_PARTITION, faults.NET_CONNECT):
            try:
                fault_injector.check(pt, peer=str(address))
            except NetDelay as e:
                time.sleep(type(e).SECONDS)
            except InjectedFault as e:
                err = TransportError(
                    f"injected {pt} refused connect to {address}")
                err.__cause__ = e
                raise err
    try:
        sock = socket.create_connection(address, timeout=timeout)
    except OSError as e:
        raise TransportError(
            f"connect to {address} failed: {e}") from e
    sock.settimeout(None)
    return Connection(sock, fault_injector=fault_injector,
                      registry=registry,
                      peer=f"{address[0]}:{address[1]}",
                      max_frame_bytes=max_frame_bytes)
