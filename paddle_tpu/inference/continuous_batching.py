"""Continuous-batching decode server (slot-based, static shapes).

The reference's serving depth is AnalysisPredictor + the fused-transformer
decode op driven per request (analysis_predictor.h:95,
fused_multi_transformer_op.cu). The TPU-native upgrade is CONTINUOUS
BATCHING: a fixed pool of decode slots steps as ONE batched XLA program
every tick; finished slots are refilled from the queue without stopping
the others. Static shapes throughout (slot count, cache length) — no
recompiles as requests come and go; per-slot positions ride the vector-t
decode step fns (models/generation.py).

Host/device split: the device does batched prefill + batched decode
steps; the host only assigns slots, harvests finished rows, and swaps
new prompts in — O(requests), not O(tokens), host work.
"""
import threading
import time as _time_mod

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import unwrap
from .kv_cache import OutOfPages
from ..reliability import (CallbackError, CircuitOpenError, DEAD,
                           DEGRADED, DRAINING, DeadlineExceeded, HEALTHY,
                           HealthMonitor, MigrationError, PreemptedError,
                           QueueFullError, ReliabilityError,
                           RequestCancelled, ServeSupervisor, ServerClosed,
                           faults)
from ..telemetry.clock import MonotonicClock

__all__ = ["ContinuousBatchingServer", "PreemptionPolicy", "PoolBalance"]

# Process-wide cache of jitted fused-tick programs, keyed by (bundle
# entry, sampling params): N servers over the same model share one
# compile per geometry point instead of re-tracing per instance.
# Bounded (oldest-out, like the decode-bundle LRU in generation.py):
# each entry's fused_fn closes over a full stacked weight copy, so an
# unbounded cache would pin every model a long-lived process ever
# served. 8 covers a replica fleet's greedy + sampled pairs.
_FUSED_STEP_CACHE = {}
_FUSED_STEP_CACHE_MAX = 8


class _Pending:
    """A queued request awaiting a slot."""

    __slots__ = ("rid", "ids", "budget", "seed", "on_token", "deadline",
                 "priority", "journey")

    def __init__(self, rid, ids, budget, seed, on_token, deadline,
                 priority=0, journey=None):
        self.rid = rid
        self.ids = ids
        self.budget = budget
        self.seed = seed
        self.on_token = on_token
        self.deadline = deadline      # absolute clock time, or None
        self.priority = priority      # higher = preempted later
        self.journey = journey        # fleet trace handle (router), or
        #                               None — every emission site is
        #                               guarded, so no-journey costs
        #                               one attribute check


class _Slot:
    __slots__ = ("rid", "ids", "prompt_len", "budget", "emitted",
                 "on_token", "streamed", "deadline", "phase", "fill_pos",
                 "filled", "n_pre", "seed", "priority", "preempts",
                 "replayed", "journey", "reprefill_upto", "sent_pages")

    def __init__(self, rid, ids, prompt_len, budget, on_token=None,
                 deadline=None):
        self.rid = rid
        self.ids = ids                # prompt tokens (donated at release)
        self.prompt_len = prompt_len
        self.budget = budget          # max_new_tokens remaining
        self.emitted = []
        self.on_token = on_token
        self.streamed = 0             # tokens already sent to on_token
        self.deadline = deadline      # absolute clock time, or None
        # ragged-prefill lifecycle (dense admission completes prefill
        # atomically, so its slots are born in the "decode" phase with
        # the whole prompt marked filled)
        self.phase = "decode"         # "prefill" until first token
        self.fill_pos = prompt_len    # next prompt position to prefill
        self.filled = prompt_len      # prompt rows actually written
        self.n_pre = 0                # prefix-cache tokens reused
        self.seed = 0                 # sampling chain seed
        self.priority = 0             # preemption class (higher = safer)
        self.preempts = 0             # times this request was preempted
        self.journey = None           # fleet trace handle, or None
        self.reprefill_upto = 0       # prefill rows below this position
        #                               redo a registered prefix's
        #                               sub-page tail (ledger:
        #                               tail_reprefill, ragged mode)
        self.sent_pages = 0           # pages already shipped by a
        #                               pipelined handoff
        #                               (migrate_out(partial=True));
        #                               reset on migrate_abort so a
        #                               later full handoff re-ships
        # the partial recorded BEFORE a preemption: a resumed slot
        # replays the identical chain, so the longer of (replayed,
        # emitted) is always the request's true partial — a deadline/
        # cancel/hard-stop mid-replay must not hand the waiter fewer
        # tokens than its on_token stream already delivered
        self.replayed = ()

    def partial(self):
        """The request's current partial output: replayed tokens from
        before a preemption, or the live emitted list — whichever is
        longer (they agree on the common prefix by bit-exact replay)."""
        return self.emitted if len(self.emitted) >= len(self.replayed) \
            else list(self.replayed)

    def stream(self, sink):
        """Queue this slot's unstreamed chunk on ``sink``; the server
        fires callbacks AFTER releasing its lock (a slow or blocking
        callback must not stall decode/submit/cancel). A RESUMED slot
        starts with ``streamed`` at its pre-preemption offset, so the
        replayed (bit-identical) tokens below it are never re-sent."""
        if self.on_token is None:
            return
        upto = min(len(self.emitted), self.budget)
        if upto > self.streamed:
            sink.append((self.on_token, self.rid,
                         np.asarray(self.emitted[self.streamed:upto],
                                    np.int32)))
            self.streamed = upto


class _Preempted:
    """A request parked off its slot under pool pressure, awaiting
    re-admission (``admission="optimistic"``). Carries everything a
    bit-exact replay needs: the RESOLVED sampling seed (the replayed
    chain draws identically), the ABSOLUTE deadline (time spent parked
    keeps counting), ``streamed`` (on_token never re-sends delivered
    chunks), and ``emitted`` — the longest partial so far, flushed as
    the result if the request must leave early (deadline, cancel, hard
    stop, dead-replica evacuation) before decode resumes."""

    __slots__ = ("rid", "ids", "budget", "seed", "on_token", "deadline",
                 "priority", "emitted", "streamed", "preempts", "journey")

    def __init__(self, st):
        self.rid = st.rid
        self.ids = st.ids
        self.budget = st.budget
        self.seed = st.seed
        self.on_token = st.on_token
        self.deadline = st.deadline
        self.priority = st.priority
        self.emitted = list(st.partial())
        self.streamed = st.streamed
        self.preempts = st.preempts + 1
        self.journey = st.journey


class PreemptionPolicy:
    """Victim selection for ``admission="optimistic"``: when a
    mid-decode page grow hits an exhausted pool, ``pick`` names the
    slot whose pages are freed. The default order sacrifices the LEAST
    valuable work first — lowest ``priority`` class, then fewest
    tokens generated (least recompute thrown away), then the youngest
    request (highest rid) so ties are deterministic and two same-seed
    runs preempt identically.

    The growing slot is itself a candidate: when it ranks last it
    parks ITSELF instead of evicting more valuable work. That makes
    the ranking a strict total order over live slots, so the top
    request is never preempted, only gains tokens, and finishes —
    global progress follows by induction no matter how hard the pool
    thrashes (recompute-preemption as in paged-attention serving
    stacks, PAPERS.md)."""

    def key(self, slot, st):
        """Sort key over live slots; the MINIMUM is preempted first.
        Work is the request's TRUE partial (``st.partial()`` — the
        longer of the pre-preemption tokens and the live replay), not
        the raw replay progress: a resumed victim early in its replay
        must keep the seniority of the work it already did once, or
        every squeeze would re-pick the same just-resumed request and
        throw its replay away again (thrash/starvation of exactly the
        requests that already lost the gamble)."""
        return (st.priority, len(st.partial()), -st.rid)

    def pick(self, grower, candidates):
        """``candidates`` is ``[(slot, _Slot)]`` for every live slot,
        the grower included. Returns the victim slot id (possibly
        ``grower`` itself), or None when there is nothing to free."""
        if not candidates:
            return None
        return min(candidates, key=lambda c: self.key(*c))[0]


class PoolBalance(tuple):
    """``pool_balance()``'s result: a plain ``(free, live, pinned,
    cached)`` 4-tuple (existing unpacks keep working), with optimistic-
    admission state riding as ATTRIBUTES: ``preempted`` — requests
    currently parked on the preempted queue (their pages are already
    donated or freed, so they contribute nothing to ``live``) — and
    ``preemptions`` — cumulative victims preempted so far.

    On a sharded pool (mesh serving) the per-shard view rides as
    attributes too: ``num_shards`` (1 = unsharded/replicated),
    ``per_shard`` — one ``{"free", "live", "pinned", "cached"}`` dict
    per shard — and ``shard_page_bytes``, the pool bytes actually
    resident on one shard's device. Because pages shard on the KV-HEAD
    dim, every shard holds the same page set: the per-shard counts are
    balanced by construction, and this view exists so dashboards,
    storms, and postmortems can ASSERT that instead of assuming it
    (a future page-partitioned layout reports through the same
    surface).

    Tiered KV (ISSUE 17) rides as attributes too: ``host`` —
    host-resident radix-tree nodes (spilled pages; they hold NO device
    page, so they are outside the 4-tuple, which keeps summing to the
    usable pool) — and ``host_bytes``, the host tier's buffer bytes.
    Chaos suites assert ``host == 0 and host_bytes == 0`` after a
    drain + full eviction proves neither tier leaked."""

    def __new__(cls, free, live, pinned, cached, preempted=0,
                preemptions=0, num_shards=1, per_shard=(),
                shard_page_bytes=None, host=0, host_bytes=0):
        self = super().__new__(cls, (free, live, pinned, cached))
        self.preempted = preempted
        self.preemptions = preemptions
        self.num_shards = num_shards
        self.per_shard = tuple(per_shard)
        self.shard_page_bytes = shard_page_bytes
        self.host = host
        self.host_bytes = host_bytes
        return self


class ContinuousBatchingServer:
    """Serve ``model.generate``-compatible requests through a fixed slot
    pool. Results are bit-identical to a solo ``model.generate`` call —
    greedy trivially (slots are row-wise independent), and sampled
    decoding too: each request carries its own PRNG chain, split in the
    same pattern as ``sample_generate``, so ``submit(..., seed=s)``
    draws exactly what ``generate(..., do_sample=True, seed=s)`` draws.

    >>> srv = ContinuousBatchingServer(model, max_slots=4,
    ...                                max_cache_len=256)
    >>> rid = srv.submit(prompt_ids, max_new_tokens=32)
    >>> outs = srv.run()            # {rid: np.ndarray of new tokens}

    ``cache_backend="paged"`` swaps the dense ``[slots, max_cache_len]``
    KV buffers for a global page pool + per-slot block tables (ragged
    paged attention; ops/pallas/paged_attention.py, inference/
    kv_cache.py): cache HBM and decode attention bandwidth scale with
    ACTUAL sequence lengths, ``num_pages`` (default: worst case, every
    slot maxed out) sizes the pool to the real working set, registered
    prefixes are stored once and page-shared across slots, and tokens
    stay bit-identical to the dense backend. When the pool is full,
    admission waits (FIFO) for a harvest to free pages.

    With ``auto_prefix_cache=True`` (the paged default; see
    inference/prefix_cache.py) prefix reuse needs no operator calls at
    all: every finished request donates its full prompt pages into a
    radix tree keyed by token content, every admission looks up the
    longest cached page-aligned prefix automatically and prefills only
    the remainder, and unpinned cached pages are evicted LRU whenever
    the allocator runs short — the cache soaks up idle pool capacity
    and shrinks under load with zero correctness impact (auto hits are
    bit-identical to cold runs). ``register_prefix`` entries live in
    the same tree as PINNED nodes that eviction never touches.

    Paged serving prefills RAGGED by default (``prefill_mode="ragged"``):
    admissions only reserve pages, and every tick runs the next chunk
    of ALL mid-prefill slots as ONE packed launch straight into pool
    pages (ops/pallas/ragged_prefill.py) — several admissions per tick,
    no dense batch-1 seed/gather/scatter detour on prefix-cache hits,
    and Sarathi-style interleaving: ``prefill_tokens_per_tick`` (default
    ``max_cache_len``) bounds the prefill work done per tick so a long
    prompt streams in across ticks while in-flight slots keep decoding
    every tick. ``max_admissions_per_tick`` caps reservations per
    scheduling pass; ``prefill_mode="dense"`` restores the PR-5
    per-admission dense prefill (the dispatch-count baseline;
    ``prefill_chunk`` only applies there and to ``register_prefix``).
    Tokens are bit-identical across all three of dense backend, paged+
    dense prefill, and paged+ragged prefill.

    ``serving_mode="fused"`` (paged + ragged only; default
    ``"split"``) folds the WHOLE tick into ONE device program
    (ops/pallas/fused_tick.py; FlashFuser / "Tile-Level Activation
    Overlap", PAPERS.md): every mid-prefill slot's next prompt chunk
    and every live slot's s=1 decode row run as one launch — rope,
    cache-page writes, online-softmax paged attention, logits and the
    SAMPLING epilogue all inside it — and the per-tick inputs (packed
    tokens, offsets, the live block-table slice, PRNG keys) ride as
    program arguments instead of device-resident state, so steady-
    state AND admission ticks dispatch exactly once ({"fused": 1} in
    the tick profile). The launch's DMA schedule covers only LIVE
    pages per slot, lifting the split kernels' full-table-width
    masked-DMA cut (the goodput ledger's ``skipped_page_dma`` shrinks
    to the schedule's ladder pad), and mid-prefill slots are real
    prefill rows instead of null-redirected decode rides. Tokens stay
    bit-identical to the split path (greedy and seeded sampling):
    decode rows route through an s=1-shaped program on the XLA
    fallback, prefill rows keep the min-2 chunk parity rule, and the
    in-program sampling replays the exact host-side PRNG chains.
    Geometry (chunk width, live table width, schedule length) rides
    pow2 ladders — compiles stay O(log); ``tick_block`` must be 1
    (multi-token decode rows are the speculative-verify shape,
    ROADMAP item 6).

    ``admission="optimistic"`` (paged backend only; default
    ``"reserve"``) lifts the full-extent admission pessimism: a
    request is admitted with only its PROMPT pages plus
    ``headroom_pages``, decode grows its block table page-by-page on
    demand, and when a grow finds the pool empty the
    ``preemption_policy`` picks victims — lowest priority class first,
    then fewest tokens generated, deterministic ties — frees their
    pages (written prompt prefixes are donated into the prefix cache
    first), and parks them on a preempted queue. Re-admission REPLAYS
    the victim bit-exactly: the resolved seed restarts the identical
    sampling chain, the donated pages usually auto-hit so the prompt
    is not re-prefilled, and streamed callbacks resume at their old
    offset — under pressure the server degrades throughput, never
    correctness, and no request ever fails because the gamble lost.
    ``submit(priority=...)`` sets the preemption class (higher = safer,
    admitted first); admission order becomes priority-aware FIFO.

    ``telemetry`` (``paddle_tpu.telemetry.ServerTelemetry``, or ``True``
    for a default one) turns on SLO instrumentation: per-request
    lifecycle spans and TTFT/TPOT/queue-wait histograms, per-tick
    latency/occupancy, page-pool gauges and prefix-cache counters —
    scrape via ``telemetry.MetricsServer(srv.telemetry.registry)``.
    Host-side only; with the default ``telemetry=None`` the hot path
    pays a single attribute check, no locks and no clock reads.

    ``recorder`` (``telemetry.FlightRecorder``, or ``True``) adds the
    flight-recorder layer: a bounded ring of structured events
    (admissions, grows, preemptions/replays, evictions, per-tick
    dispatch profiles, health/breaker flips) and postmortem bundles
    captured on breaker open, request failure, and ``kill()`` —
    ``srv.postmortems()``, or ``/debug/postmortem`` via
    ``serve_metrics``. A disabled recorder is treated exactly like
    the default None (same zero-cost contract as telemetry).

    ``ledger`` (``telemetry.GoodputLedger``, or ``True``) turns on the
    goodput ledger: every device token each tick is attributed to
    exactly one kind — committed work (``goodput``) or a named waste
    reason (``null_redirect`` / ``chunk_pad`` / ``skipped_page_dma`` /
    ``replay`` / ``tail_reprefill`` / ``block_waste``) — published as
    ``server_tokens_total{kind}``, the per-tick
    ``serving_goodput_ratio`` gauge, ``srv.goodput()`` (also
    ``/stats["goodput"]``), and a ``goodput`` postmortem section.
    Kinds sum to the tick's total device tokens (conservation is
    test-asserted); a disabled ledger is treated exactly like None.

    ``costs`` (``telemetry.CostCatalog``, or ``True``) turns on the
    device-cost ledger + compile watch: every jitted serving program
    (the decode block, each ragged-prefill chunk width) is priced ONCE
    per shape signature from the compiler's own
    ``cost_analysis`` at compile time, every dispatch is charged FLOPs
    + HBM bytes (``server_flops_total{op}`` /
    ``server_hbm_bytes_total{op}``, ``serving_mfu``), compiles are
    timed (``server_compiles_total{op}``, ``serving_compile_seconds``)
    and a compile AFTER warmup lands as a ``compile`` flight-recorder
    event with ``recompile=True`` plus a ``compile_stall`` journey
    phase on every request parked behind it, and each tick's wall is
    split into phases (``serving_tick_phase_seconds{phase}``) —
    ``srv.device_costs()`` (also ``/stats["costs"]``) and a ``costs``
    postmortem section. A disabled catalog is treated exactly like
    None (zero clock reads / locks on the tick path).

    ``journeys`` (``telemetry.JourneyRecorder``, or ``True``) lets a
    STANDALONE server mint its own request journeys: ``submit()``
    begins one per request unless a router-supplied handle arrives via
    ``submit(journey=)``, and ``srv.journey(rid)`` returns the
    timeline (also ``/debug/journey/<rid>``).

    Reliability (paddle_tpu.reliability): ``submit(deadline_s=...)``
    bounds waiting, ``max_queue`` + ``shed_policy`` bound the queue,
    the ``start()`` serve thread is SUPERVISED (``retry_policy`` /
    ``breaker`` drive backoff and circuit breaking; a tick exception
    retries instead of killing the thread), ``stop(drain=True)``
    drains gracefully, ``srv.health`` walks
    healthy/degraded/draining/dead (also ``/healthz`` via
    ``serving.serve_metrics``), and ``fault_injector`` arms named
    chaos failure points (prefill / decode tick / page alloc /
    on_token). All typed failures reach waiters as
    ``reliability.ReliabilityError`` subclasses from ``wait()``.
    """

    def __init__(self, model, max_slots=4, max_cache_len=256,
                 do_sample=False, temperature=1.0, top_k=0, top_p=1.0,
                 eos_token_id=None, seed=0, weight_dtype=None,
                 prefill_chunk=None, mesh=None, tick_block=1,
                 cache_dtype=None, cache_backend="dense", page_size=16,
                 num_pages=None, auto_prefix_cache=True,
                 admission="reserve", headroom_pages=1,
                 preemption_policy=None,
                 prefill_mode=None, prefill_tokens_per_tick=None,
                 max_admissions_per_tick=None, serving_mode=None,
                 telemetry=None,
                 recorder=None, ledger=None, journeys=None, costs=None,
                 host_tier=None, host_tier_bytes=None,
                 max_queue=None, shed_policy="reject",
                 retry_policy=None, breaker=None, fault_injector=None,
                 clock=None, role="hybrid"):
        if role not in ("prefill", "decode", "hybrid"):
            raise ValueError(
                "role must be 'prefill', 'decode' or 'hybrid', got "
                f"{role!r}")
        # disaggregated serving (ISSUE 20): the role is a PLACEMENT
        # hint the router reads — a "prefill" specialist runs ragged
        # prefill and hands finished prompt pages to decode replicas;
        # its one hard rule is refusing decode-phase migrate_in (it
        # still decodes locally when the fleet degrades to hybrid
        # routing). "decode" is advisory only.
        self.role = role
        self.model = model
        self.mesh = mesh
        self.max_slots = int(max_slots)
        self.max_cache_len = int(max_cache_len)
        self.eos_token_id = eos_token_id
        self.do_sample = bool(do_sample)
        self._temperature = float(temperature)
        self._top_k = int(top_k)
        self._top_p = float(top_p)
        self._seed = int(seed)
        self._keys = jnp.zeros((int(max_slots), 2), jnp.uint32)
        # the dense bundle always exists: prefill (and the prefix cache)
        # run on dense batch-1 caches whatever the decode backend is
        self._bundle = model._decode_bundle(max_cache_len, weight_dtype,
                                            mesh, cache_dtype)
        (self._init_caches, self._embed_fn, self._step_fn,
         self._head_fn, self._prefill_jit) = self._bundle
        self._prefill_chunk = prefill_chunk
        self.tick_block = max(1, int(tick_block))

        if cache_backend not in ("dense", "paged"):
            raise ValueError(f"cache_backend must be 'dense' or 'paged', "
                             f"got {cache_backend!r}")
        self.cache_backend = cache_backend
        self._kv = None
        if cache_backend == "paged":
            # decode runs on a global K/V page pool addressed through
            # per-slot block tables (ragged paged attention); the pool —
            # not slots x max_cache_len — is the cache HBM budget, so it
            # can be sized to the ACTUAL token working set
            from .kv_cache import PagedKVCache
            page_size = int(page_size)
            if self.max_cache_len % page_size:
                raise ValueError(
                    f"page_size ({page_size}) must divide max_cache_len "
                    f"({self.max_cache_len})")
            pages_per_slot = self.max_cache_len // page_size
            if num_pages is None:     # worst case: every slot maxed out
                num_pages = self.max_slots * pages_per_slot + 1
            self.page_size = page_size
            # the paged kernels' grid covers the FULL block-table width
            # per slot — the goodput ledger's skipped-page-DMA model
            self._bt_pages = pages_per_slot
            self._paged_bundle = model._decode_bundle(
                max_cache_len, weight_dtype, mesh, cache_dtype,
                cache_backend="paged", page_size=page_size,
                num_pages=int(num_pages))
            self._step_fn = self._paged_bundle[2]
            self._kv = PagedKVCache(int(num_pages), page_size,
                                    self.max_slots, pages_per_slot,
                                    fault_injector=fault_injector)
            self._caches = self._paged_bundle[0](self.max_slots)
            # how many ways the pool actually sharded (1 = replicated
            # fallback: kv heads not divisible by the mp axis) — the
            # host-side bookkeeping's ONLY mesh knowledge, feeding the
            # per-shard balance views and the cost-op namespacing
            from ..models.generation import paged_pool_shards
            self._pool_shards = paged_pool_shards(
                mesh, int(self._caches["pool"]["k"].shape[3]))
            # host KV tier (kv_tier.HostTier): eviction SPILLS cold
            # prefix pages to checksummed host buffers instead of
            # dropping them, and admissions hitting a spilled run
            # restore it into fresh pool pages. True builds a default
            # tier (host_tier_bytes= bounds it; None = unbounded);
            # None/disabled keeps eviction exactly as before — zero
            # locks, zero clock reads, structurally free, the same
            # contract as ledger/recorder/costs
            if host_tier is None and host_tier_bytes is not None:
                host_tier = True
            if host_tier is True:
                from .kv_tier import HostTier
                host_tier = HostTier(budget_bytes=host_tier_bytes,
                                     fault_injector=fault_injector)
            self.host_tier = host_tier
            self._host = host_tier if (host_tier is not None
                                       and host_tier.enabled) else None
            if self._host is not None and self._host._faults is None:
                # like the recorder: a bare tier adopts the server's
                # injector so tier.spill/tier.restore storms need no
                # extra wiring
                self._host._faults = fault_injector
            # the radix tree indexes EVERY page-granular prefix in the
            # pool: register_prefix entries live in it pinned; with
            # auto_prefix_cache (default) finished requests donate
            # their prompt pages into it and lookups happen on every
            # admission — unpinned entries are evicted LRU whenever
            # the allocator runs short (demoted to the host tier when
            # one is attached)
            from .prefix_cache import PrefixCache
            self._prefix = PrefixCache(self._kv,
                                       fault_injector=fault_injector,
                                       host_tier=self._host,
                                       spill=self._spill_payload)
            self._kv.reclaimer = self._reclaim_pages
            self._auto_prefix = bool(auto_prefix_cache)
            self._ragged_fn = (self._paged_bundle[5]
                               if len(self._paged_bundle) > 5 else None)
            self._fused_fn = (self._paged_bundle[6]
                              if len(self._paged_bundle) > 6 else None)
        else:
            if host_tier is True or (host_tier is not None
                                     and host_tier.enabled):
                raise ValueError("host_tier= needs cache_backend="
                                 "'paged' (the tier spills pool pages)")
            self.host_tier = None
            self._host = None
            self.page_size = None
            self._bt_pages = None
            self._pool_shards = 1
            self._caches = self._init_caches(self.max_slots)
            self._prefix = None
            self._auto_prefix = False
            self._ragged_fn = None
            self._fused_fn = None
        # ------------------------------------------------ prefill mode
        # "ragged" (the paged default): admissions reserve pages only;
        # their prompt chunks run BATCHED as one ragged launch per tick
        # straight into pool pages — no dense batch-1 seed/gather/
        # scatter detour — interleaved with decode under a token budget.
        # "dense" keeps the PR-5 per-admission dense prefill (the only
        # mode for the dense cache backend, and the baseline the
        # benchmarks compare dispatch counts against).
        if prefill_mode is None:
            prefill_mode = "ragged" if self._ragged_fn is not None \
                else "dense"
        if prefill_mode not in ("dense", "ragged"):
            raise ValueError(f"prefill_mode must be 'dense' or 'ragged',"
                             f" got {prefill_mode!r}")
        if prefill_mode == "ragged":
            if cache_backend != "paged":
                raise ValueError("prefill_mode='ragged' needs "
                                 "cache_backend='paged' (prefill writes "
                                 "straight into pool pages)")
            if self._ragged_fn is None:
                raise ValueError(
                    "prefill_mode='ragged' but this model's paged "
                    "decode bundle has no ragged-prefill entry point "
                    "(6th element); use prefill_mode='dense'")
        self.prefill_mode = prefill_mode
        self._ragged = prefill_mode == "ragged"
        if prefill_tokens_per_tick is None:
            prefill_tokens_per_tick = self.max_cache_len
        self._prefill_budget = int(prefill_tokens_per_tick)
        if self._prefill_budget < 1:
            raise ValueError("prefill_tokens_per_tick must be >= 1")
        self._admit_cap = None if max_admissions_per_tick is None \
            else int(max_admissions_per_tick)
        if self._admit_cap is not None and self._admit_cap < 1:
            raise ValueError("max_admissions_per_tick must be >= 1 "
                             "(0 would admit nothing, forever)")
        # ------------------------------------------------ admission mode
        # "reserve" (default): admission takes a request's FULL extent
        # (prompt + budget) up front — decode can never hit an empty
        # pool, but concurrency is capped by the WORST-case decode
        # length even though most requests finish far earlier.
        # "optimistic": admission reserves only the prompt pages plus
        # ``headroom_pages``; decode grows each slot page-by-page on
        # demand, and when the pool runs dry mid-tick the
        # ``preemption_policy`` frees victims — parked on a preempted
        # queue and re-admitted with a BIT-EXACT replay (resolved seed
        # + prefix-cache-assisted recompute), so pressure degrades
        # throughput, never correctness.
        if admission not in ("reserve", "optimistic"):
            raise ValueError(f"admission must be 'reserve' or "
                             f"'optimistic', got {admission!r}")
        if admission == "optimistic" and cache_backend != "paged":
            raise NotImplementedError(
                "admission='optimistic' needs cache_backend='paged': "
                "the dense backend allocates every slot's full "
                "[max_cache_len] KV rows up front, so there is no pool "
                "to admit optimistically against — virtualizing dense "
                "slot buffers is the same page-pool work as the "
                "quantized paged pool in ROADMAP (item 3); use "
                "cache_backend='paged'")
        self.admission = admission
        self._optimistic = admission == "optimistic"
        self._headroom_pages = int(headroom_pages)
        if self._headroom_pages < 0:
            raise ValueError("headroom_pages must be >= 0")
        self._preempt_policy = preemption_policy \
            if preemption_policy is not None else PreemptionPolicy()
        # ------------------------------------------------ serving mode
        # "split" (default): the PR-6 tick — one ragged-prefill launch
        # for the admission wave, the s=1 decode program for live
        # slots, batched state pushes between them. "fused" (ISSUE 14):
        # the WHOLE tick is ONE program — prefill chunks and decode
        # rows packed into a single fused-tick launch whose DMA
        # schedule covers only live pages (ops/pallas/fused_tick.py),
        # sampling folded into the same program, per-tick inputs
        # (tokens, offsets, live block-table slice, PRNG keys) riding
        # as program arguments instead of device-resident state — the
        # per-tick dispatch histogram collapses to {"fused": 1} on
        # steady-state AND admission ticks.
        if serving_mode is None:
            serving_mode = "split"
        if serving_mode not in ("split", "fused"):
            raise ValueError(f"serving_mode must be 'split' or "
                             f"'fused', got {serving_mode!r}")
        if serving_mode == "fused":
            if cache_backend != "paged":
                raise ValueError(
                    "serving_mode='fused' needs cache_backend='paged' "
                    "(the fused tick writes straight into pool pages "
                    "through a live-page DMA schedule)")
            if not self._ragged:
                raise ValueError(
                    "serving_mode='fused' needs prefill_mode='ragged' "
                    "(the fused launch packs the ragged scheduler's "
                    "prompt chunks; dense prefill is the split-mode "
                    "baseline)")
            if self._fused_fn is None:
                raise ValueError(
                    "serving_mode='fused' but this model's paged "
                    "decode bundle has no fused-tick entry point "
                    "(7th element); use serving_mode='split'")
            if mesh is not None:
                raise NotImplementedError(
                    "fused+mesh is not wired yet: the sharded paged "
                    "pool serves through the SPLIT tick (ragged "
                    "prefill + decode programs shard per kv-head with "
                    "block tables replicated), but the fused tick's "
                    "live-page DMA schedule and folded sampling "
                    "epilogue still assume one device — making the "
                    "megakernel shard-aware is the mesh half of "
                    "ROADMAP item 2 on top of item 1's sharded pool; "
                    "use serving_mode='split' on meshes")
            if self.tick_block != 1:
                raise NotImplementedError(
                    "serving_mode='fused' runs ONE decode row per slot "
                    "per launch; tick_block > 1 needs multi-token rows "
                    "per slot — exactly the ragged s>1 verify shape "
                    "speculative decoding adds (ROADMAP item 6: "
                    "verify rows fold into the fused tick); use "
                    "tick_block=1 or serving_mode='split'")
        self.serving_mode = serving_mode
        self._fused = serving_mode == "fused"
        self._fused_jit = None    # sampling-fused tick program
        self._fused_progs = {}    # (C, W, G) -> priced program (costs=)
        # fused mode keeps the sampling PRNG keys HOST-side: they ride
        # the launch as arguments and come back with the tokens, so no
        # state_push dispatch ever fires on the tick path
        self._host_keys = np.zeros((self.max_slots, 2), np.uint32)
        self._preempted = []      # _Preempted records awaiting re-admission
        self._migrating = {}      # rid -> (slot, tele t0, prior phase):
        #                           paused slots whose gathered pages are
        #                           in flight to a sibling (migrate_out) —
        #                           settled by migrate_finish (handoff
        #                           committed, pages released/donated
        #                           here) or migrate_abort (resume
        #                           decoding — or prefilling, for an
        #                           empty-`emitted` handoff — here)
        self._staging = {}        # handle -> pipelined-restore slot
        #                           (migrate_in_begin): pages scatter in
        #                           batches while the source still
        #                           prefills; settled by
        #                           migrate_in_commit / migrate_in_abort
        self._next_xfer = 1       # staged-restore handle mint
        self._priority_seen = False   # sticky: any submit(priority != 0)
        self._prefill_fifo = []   # slot ids mid-prefill, admission order
        self._prefill_used = 0    # tokens prefilled this tick
        # slot-state updates batched into one device push per array per
        # tick (the dense path paid 3 dispatches per admission)
        self._pending_tok = {}
        self._pending_t = {}
        self._pending_key = {}
        self._tok = jnp.zeros((self.max_slots,), jnp.int32)
        self._t = jnp.zeros((self.max_slots,), jnp.int32)
        self._active = np.zeros((self.max_slots,), bool)   # host-side
        self._slots = [None] * self.max_slots
        self._queue = []          # (rid, ids_np, max_new_tokens)
        self._results = {}
        self._next_rid = 0
        self._decode_jit = None
        self._prefixes = []   # [(ids, cache_rows, last_logits, pages)]
        self.stats = {"prefill_tokens": 0, "prefix_hit_tokens": 0,
                      "prefix_auto_hits": 0, "prefix_auto_hit_tokens": 0,
                      "admissions": 0, "prefill_dispatches": 0,
                      "prefill_wall_s": 0.0, "tick_dispatches": 0,
                      # admission="optimistic" accounting
                      "preemptions": 0, "preempt_resumed": 0,
                      "grow_pages": 0, "headroom_pages": 0,
                      # live KV-page migration accounting: handoffs
                      # committed as the SOURCE / degraded to
                      # evacuate+replay / restored as the TARGET
                      "migrations": 0, "migration_fallbacks": 0,
                      "migrated_in": 0,
                      # disaggregated prefill handoff accounting:
                      # partial page batches shipped as the source
                      # (migrate_out(partial=True)) / staged batches
                      # landed as the target (migrate_in_pages)
                      "handoff_pages_out": 0, "handoff_pages_in": 0}
        # telemetry (paddle_tpu.telemetry.ServerTelemetry): True builds
        # a default-enabled one; None (default) keeps the hot path at
        # a single attribute check — no locks, no clock reads
        if telemetry is True:
            from ..telemetry import ServerTelemetry
            telemetry = ServerTelemetry()
        self.telemetry = telemetry
        self._tele = telemetry if (telemetry is not None
                                   and telemetry.enabled) else None
        # one time base for everything (events must correlate with
        # spans/deadlines in a postmortem, and FakeClock tests need
        # determinism): explicit clock > telemetry's > monotonic
        self._clock = clock if clock is not None else (
            telemetry.clock if self._tele is not None else MonotonicClock())
        # flight recorder (telemetry.FlightRecorder): structured event
        # ring + postmortem bundles. True builds a default one on the
        # server's clock; a DISABLED recorder is treated exactly like
        # None, so the hot path pays one `is None` check — no locks,
        # no clock reads
        if recorder is True:
            from ..telemetry import FlightRecorder
            recorder = FlightRecorder(clock=self._clock)
        self.recorder = recorder
        self._rec = recorder if (recorder is not None
                                 and recorder.enabled) else None
        # goodput ledger (telemetry.GoodputLedger): per-tick device-
        # token attribution — goodput vs null_redirect / chunk_pad /
        # skipped_page_dma / replay / tail_reprefill / block_waste.
        # True builds one on the telemetry registry (metrics ride
        # server_tokens_total{kind} + serving_goodput_ratio); a
        # DISABLED ledger is treated exactly like None — one `is None`
        # check per site, no locks, no clock reads (it never reads a
        # clock at all)
        if ledger is True:
            from ..telemetry import GoodputLedger
            ledger = GoodputLedger(
                registry=self._tele.registry
                if self._tele is not None else None)
        self.ledger = ledger
        self._led = ledger if (ledger is not None
                               and ledger.enabled) else None
        # device-cost catalog + compile watch (telemetry.CostCatalog):
        # every jitted serving program priced once per shape signature
        # at compile time (lower/compile/cost_analysis — the catalog
        # keeps the executable, so pricing costs no duplicate compile),
        # every dispatch charged FLOPs + HBM bytes, recompiles after
        # warmup surfaced, tick wall split into phases. True builds one
        # on the telemetry registry + server clock; a DISABLED catalog
        # is treated exactly like None — one `is None` check per site,
        # zero locks, zero clock reads on the tick path
        if costs is True:
            from ..telemetry import CostCatalog
            costs = CostCatalog(
                registry=self._tele.registry
                if self._tele is not None else None, clock=self._clock)
        self.costs = costs
        self._costs = costs if (costs is not None
                                and costs.enabled) else None
        self._phase_timer = None    # per-tick, set by _step_locked
        self._decode_prog = None    # priced decode program (static sig)
        self._kv_row_nbytes = None  # lazy: bytes per K+V token row
        # journey recorder for STANDALONE servers (closes the PR-9
        # "router-minted only" cut): submit() mints "s<rid>" journeys
        # when no router-supplied handle arrives, and journey(rid)
        # returns the timeline. Router-fronted servers keep receiving
        # handles via submit(journey=) — those always win.
        if journeys is True:
            from ..telemetry import JourneyRecorder
            journeys = JourneyRecorder(clock=self._clock)
        self.journeys = journeys
        self._jrec = journeys if (journeys is not None
                                  and journeys.enabled) else None
        # per-tick host->device dispatch profile {op: count} — the
        # dispatches-per-decode-tick baseline ROADMAP item 4 is
        # measured against; reset at each tick, published to telemetry
        # + recorder when nonempty (plain dict ops: always maintained,
        # costs no locks/clock)
        self._tick_disp = {}
        if fault_injector is not None:
            # chaos storms become VISIBLE: fires publish to this
            # server's registry and land in its flight recorder (an
            # injector shared across servers keeps the first recorder
            # it was given)
            if self._tele is not None \
                    and hasattr(fault_injector, "publish_to"):
                fault_injector.publish_to(self._tele.registry)
            if self._rec is not None \
                    and getattr(fault_injector, "recorder", None) is None:
                fault_injector.recorder = self._rec
        self._failures = {}   # rid -> admission exception (ADVICE r5 #2)
        self._run_failures = {}   # last run()'s drained failures
        # submit()/cancel() may come from request threads while a serve
        # thread drives step(); one lock covers the queue/slot state and
        # a condition on it wakes wait()ers at harvest time
        self._lock = threading.RLock()
        self._done_cv = threading.Condition(self._lock)
        self._stop = threading.Event()
        self._thread = None
        self._thread_error = None
        self._deferred_cbs = []   # (cb, rid, tokens) fired OUTSIDE the lock
        # ------------------------------------------------- reliability
        # admission control: a bounded queue sheds instead of growing
        # without limit under overload; deadlines bound waiting
        if shed_policy not in ("reject", "evict_oldest"):
            raise ValueError(f"shed_policy must be 'reject' or "
                             f"'evict_oldest', got {shed_policy!r}")
        self._max_queue = None if max_queue is None else int(max_queue)
        self._shed_policy = shed_policy
        self._faults = fault_injector
        self._sup = ServeSupervisor(retry=retry_policy, breaker=breaker)
        self._health = HealthMonitor(on_change=self._publish_health)
        self._accepting = True     # False while draining / after stop
        self._draining = False
        if self._tele is not None:
            self._tele.set_health(HEALTHY)

    # ------------------------------------------------------ prefix cache
    def register_prefix(self, prefix_ids):
        """Prefill a shared prompt prefix (e.g. a system prompt) ONCE and
        reuse its KV rows for every later request that starts with it —
        admission then only prefills the remainder. Longest registered
        match wins. Returns the prefix length; the entry it pins is
        PERMANENT — unlike automatically cached (donated) pages, pinned
        entries are never evicted, whatever the pool pressure. Safe to
        call while a serve thread is decoding (the lock serializes it
        against ticks: the paged path writes pool pages and takes
        allocator pages, both of which would otherwise race the
        donating decode program). Paged backend: full pages the auto
        prefix cache already holds for these tokens are adopted (and
        pinned) rather than re-allocated."""
        ids = np.asarray(unwrap(prefix_ids)).astype(np.int32).reshape(-1)
        T = ids.shape[0]
        if T + 1 > self.max_cache_len:
            raise ValueError(f"prefix ({T}) leaves no room in "
                             f"max_cache_len ({self.max_cache_len})")
        with self._lock:
            for pre_ids, _, _, _ in self._prefixes:
                # idempotent: re-registering (e.g. a client retry) must
                # not re-prefill or pin a second, unreachable page set
                if (pre_ids.shape[0] == T
                        and np.array_equal(pre_ids, ids)):
                    return T
            if self._prefill_chunk and not self._ragged:
                # a queued request was bound-checked at submit against
                # the prefixes registered THEN; refuse a new prefix
                # whose remainder-chunk pad would overflow its rows
                # mid-admission (ADVICE r5 #2). Ragged admission never
                # pads a remainder (chunking is the per-tick token
                # budget, cut at any position), so no such hazard.
                for item in self._queue:
                    q_ids = item.ids
                    Tq = q_ids.shape[0]
                    if Tq <= T or not np.array_equal(q_ids[:T], ids):
                        continue
                    cur = self._match_prefix(q_ids)
                    if cur is not None and cur[0].shape[0] >= T:
                        continue    # a longer match still wins
                    rpad = self._chunk_pad(Tq - T)
                    if Tq + rpad > self.max_cache_len:
                        raise ValueError(
                            f"registering this {T}-token prefix "
                            f"would pad the queued {Tq}-token "
                            f"request's remainder prefill {rpad} "
                            f"rows past max_cache_len "
                            f"({self.max_cache_len}) — register "
                            f"prefixes before submitting")
            logits, caches1 = self.model._run_prefill(
                self._bundle, ids[None], chunk=self._prefill_chunk)
            self.stats["prefill_tokens"] += T
            if self._tele is not None:
                self._tele.add_prefill_tokens(T)
            # dense prefill mode seeds admissions from these retained
            # rows/logits; ragged mode matches through the pinned tree
            # pages alone and never reads them — retaining a full
            # per-layer dense KV copy of the prefix for the server's
            # lifetime would be pure HBM waste there
            rows = None if self._ragged else jax.tree_util.tree_map(
                lambda c: c[:, :, :T], caches1)
            if self._ragged:
                logits = None
            pages, run, own, pin_delta = [], [], [], 0
            if self._kv is not None:
                # store the prefix's FULL pages once in the pool; every
                # slot that hits the prefix points its block table at
                # them. The radix tree is the page index: nodes the
                # auto cache already donated for these tokens are
                # adopted (pinned below), only the missing tail is
                # freshly allocated and filled
                nfull = T // self._kv.page_size
                if nfull:
                    aligned = ids[:nfull * self._kv.page_size]
                    run = self._prefix.node_run(aligned)
                    pin_delta = nfull - sum(1 for nd in run if nd.pinned)
                    if nfull > len(run):
                        # the adopted run must survive the allocation's
                        # own LRU reclaim sweep
                        self._prefix.protect(run)
                        try:
                            own = self._kv.alloc(nfull - len(run))
                        finally:
                            self._prefix.protect(())
                    pages = [nd.page for nd in run] + own
            entry = (ids, rows, logits, pages)
            self._prefixes.append(entry)
            self._prefixes.sort(key=lambda e: -e[0].shape[0])
            if self._kv is not None and pages:
                # pinning shrinks the pool for everyone else: a queued
                # request that can no longer EVER fit would silently
                # starve the FIFO — refuse the registration instead
                usable = self._kv.num_pages - 1 \
                    - (self._prefix.pinned_pages + pin_delta)
                for item in list(self._queue) + list(self._preempted):
                    # parked preempted requests must stay re-admittable
                    # too: their FULL extent is the binding bound (the
                    # top-ranked one must be able to run to completion)
                    q_ids = item.ids
                    q_need = self._request_pages(
                        q_ids, item.budget, self._match_prefix(q_ids))
                    if q_need > usable:
                        self._prefixes = [e for e in self._prefixes
                                          if e is not entry]
                        if own:
                            self._kv.release(own)
                        raise ValueError(
                            f"registering this {T}-token prefix pins "
                            f"{len(pages)} pages and would strand an "
                            f"already-queued request needing "
                            f"{q_need} of "
                            f"{usable} usable pages — grow num_pages "
                            f"or register prefixes before submitting")
                if own:
                    self._fill_pages(caches1, own,
                                     len(run) * self._kv.page_size)
                self._prefix.extend_pinned(
                    ids[:len(pages) * self._kv.page_size], run, own)
                self._prefix.flush_sketch()
            self._pool_gauges()
        return T

    def _chunk_pad(self, seg_len):
        """Rows the chunked prefill pads past ``seg_len`` — zero when
        the segment runs UNCHUNKED (``seg_len <= chunk``:
        generation._run_prefill takes the direct path and writes exactly
        ``seg_len`` rows)."""
        c = self._prefill_chunk
        if not c or seg_len <= c:
            return 0
        return (-seg_len) % c

    def _match_prefix(self, ids):
        for pre_ids, rows, logits, pages in self._prefixes:
            n = pre_ids.shape[0]
            if ids.shape[0] >= n and np.array_equal(ids[:n], pre_ids):
                return pre_ids, rows, logits, pages
        return None

    # ------------------------------------------------------------ queue
    def submit(self, input_ids, max_new_tokens=32, seed=None,
               on_token=None, deadline_s=None, priority=0,
               journey=None):
        """Queue a prompt; returns a request id. The FIRST generated
        token is produced by the prefill (same contract as generate()).
        ``seed`` drives this request's sampling chain (default: the
        server seed + request id). ``on_token(rid, tokens)`` streams
        each harvested chunk (1..tick_block tokens) as it lands.

        ``priority`` (``admission="optimistic"`` only; ignored under
        ``"reserve"``) is the request's preemption class: under pool
        pressure victims are taken from the LOWEST class first, and
        admission prefers higher classes (priority-aware FIFO — same
        class keeps submit order). Whatever the pressure, every
        request's full extent must still fit the pool on its own
        (checked here), so the top-ranked request can always run to
        completion.

        ``deadline_s`` bounds the request's TOTAL time from submit: a
        request still queued when it expires fails with
        ``DeadlineExceeded`` (no prefill is wasted on it); one expiring
        mid-decode is cancelled and its PARTIAL tokens are recorded as
        the result. With ``max_queue`` set, a full queue sheds per
        ``shed_policy`` — ``"reject"`` raises ``QueueFullError`` here,
        ``"evict_oldest"`` fails the oldest queued request instead and
        accepts this one.

        ``journey`` (a ``telemetry.Journey`` handle, normally minted by
        the router and rebound per dispatch) threads this request's
        fleet timeline through admission, prefill chunks, grow/preempt/
        replay and completion; the default None costs one attribute
        check per lifecycle site."""
        ids = np.asarray(unwrap(input_ids)).astype(np.int32)
        if ids.ndim == 2:
            if ids.shape[0] != 1:
                raise ValueError("submit() takes one request; batch by "
                                 "calling submit() per row")
            ids = ids[0]
        T = ids.shape[0]
        with self._lock:
            if not self._accepting:
                raise ServerClosed(
                    f"server is {self._health.state}; not accepting "
                    f"new requests")
            if deadline_s is not None and deadline_s <= 0:
                raise DeadlineExceeded(
                    f"deadline_s={deadline_s} is already expired")
            hit = None if self._ragged else self._match_prefix(ids)
            pad = 0
            if self._prefill_chunk and not self._ragged:
                # a registered-prefix hit prefills only the REMAINDER at
                # t0=n, whose own chunk pad can exceed the full-prompt
                # pad (ADVICE r5 #2). Longest match wins at admission,
                # prefixes are never removed, and register_prefix
                # refuses new ones that would strand a queued request —
                # so the CURRENT longest match decides the bound. The
                # RAGGED path never pads: prompts are chunked by the
                # per-tick token budget at arbitrary cut points, so the
                # only bound is prompt + budget (prefill_chunk is
                # ignored at ragged admission).
                pad = self._chunk_pad(T - hit[0].shape[0]) \
                    if hit is not None else self._chunk_pad(T)
            if max(T + max_new_tokens, T + pad) > self.max_cache_len:
                seg = "prefix-remainder" \
                    if hit is not None and self._prefill_chunk else "prompt"
                raise ValueError(
                    f"prompt ({T}) + max({max_new_tokens} new tokens, "
                    f"{pad} prefill-chunk pad rows on the {seg}) "
                    f"exceeds max_cache_len ({self.max_cache_len})")
            if self._kv is not None:
                # full-extent reservation (prompt + budget): a request
                # that can never fit must fail HERE, not stall the FIFO
                # forever — pool minus prefix-pinned pages, minus the
                # pinned pages this request would itself share. Ragged
                # mode matches through the tree: only the PINNED run is
                # stable enough to count at submit time (donated pages
                # can be evicted before admission).
                if self._ragged:
                    need = self._npages_for(T + int(max_new_tokens)) \
                        - self._pinned_run_pages(ids)
                else:
                    need = self._request_pages(ids, int(max_new_tokens),
                                               hit)
                usable = self._kv.num_pages - 1 \
                    - self._prefix.pinned_pages
                if need > usable:
                    raise ValueError(
                        f"prompt ({T}) + max_new_tokens "
                        f"({max_new_tokens}) needs {need} pages beyond "
                        f"its prefix hit but only {usable} are not "
                        f"pinned by prefixes — grow num_pages")
            if (self._max_queue is not None
                    and len(self._queue) >= self._max_queue):
                # evict_oldest with nobody to evict (max_queue=0) must
                # still shed SOMETHING — fall back to rejecting
                if self._shed_policy == "reject" or not self._queue:
                    if self._tele is not None:
                        self._tele.on_shed("reject")
                    raise QueueFullError(
                        f"queue holds {len(self._queue)} requests "
                        f"(max_queue={self._max_queue}); shed_policy="
                        f"'reject' — resubmit with backoff")
                old = self._queue.pop(0)
                err = QueueFullError(
                    f"request {old.rid} evicted by a newer submit "
                    f"(queue full at max_queue={self._max_queue}, "
                    f"shed_policy='evict_oldest')")
                self._failures[old.rid] = err
                if self._tele is not None:
                    self._tele.on_shed("evict_oldest")
                    self._tele.on_admission_failure(old.rid, err)
                self._note_request_failure_locked(old.rid, err,
                                                  old.journey,
                                                  bundle=False)
                self._done_cv.notify_all()
            rid = self._next_rid
            self._next_rid += 1
            if journey is None and self._jrec is not None:
                # standalone server: mint this request's own journey
                # ("s<rid>", location "server") so journey(rid) works
                # without a router; a router-supplied handle (above)
                # always wins — the fleet timeline stays singular
                journey = self._jrec.begin(f"s{rid}", where="server")
                journey.event("submitted", rid=rid,
                              prompt_tokens=int(T))
            if seed is None:
                # default-seed rule; remote.ReplicaHost._op_submit
                # reports the same value to its client mirror — keep
                # the two in sync (tests/test_remote_replica.py pins
                # the parity)
                seed = self._seed + rid
            deadline = None if deadline_s is None \
                else self._clock.now() + float(deadline_s)
            if priority:
                self._priority_seen = True
            self._queue.append(_Pending(rid, ids, int(max_new_tokens),
                                        int(seed), on_token, deadline,
                                        int(priority), journey))
            if self._tele is not None:
                self._tele.on_submit(rid, T, len(self._queue))
            if journey is not None:
                journey.event("queued", rid=rid, prompt_tokens=int(T))
        return rid

    def cancel(self, rid):
        """Drop a request: un-queue it, or free its slot mid-decode (the
        partial result is recorded under the rid). Returns True if the
        request was found live."""
        with self._lock:
            return self._cancel_locked(rid)

    def _cancel_locked(self, rid):
        for i, item in enumerate(self._queue):
            if item.rid == rid:
                del self._queue[i]
                # a still-queued cancel produces no result; record the
                # typed failure so a blocked wait(rid) raises instead
                # of running out its timeout
                self._failures[rid] = RequestCancelled(
                    f"request {rid} cancelled while queued")
                if self._tele is not None:
                    self._tele.on_cancel(rid)
                    self._tele.set_queue_depth(len(self._queue))
                if self._rec is not None:
                    self._rec.record("cancel", rid=rid, where="queued")
                if item.journey is not None:
                    item.journey.event("cancelled")
                self._done_cv.notify_all()
                return True
        for slot in range(self.max_slots):
            st = self._slots[slot]
            if st is not None and st.rid == rid:
                # covers decoding AND mid-ragged-prefill slots (the
                # latter record an empty partial; their filled prefix
                # pages are still donated)
                if self._rec is not None:
                    self._rec.record("cancel", rid=rid,
                                     where="in_flight")
                if st.journey is not None:
                    st.journey.event("cancelled")
                self._finish_partial_locked(slot)
                if self._tele is not None:
                    self._tele.on_cancel(rid)
                    self._pool_gauges()
                # wake waiters NOW — without this a blocked wait(rid)
                # only notices the recorded partial at its next 1 s poll
                self._done_cv.notify_all()
                return True
        for i, rec in enumerate(self._preempted):
            if rec.rid == rid:
                # parked under pool pressure: mid-flight cancel
                # semantics — the pre-preemption partial is the result
                # (its pages were already donated/freed at preemption)
                del self._preempted[i]
                if self._rec is not None:
                    self._rec.record("cancel", rid=rid,
                                     where="preempted")
                self._flush_parked_locked(rec)
                if self._tele is not None:
                    self._tele.on_cancel(rid)
                    self._preempt_gauge()
                if rec.journey is not None:
                    rec.journey.event("cancelled")
                self._done_cv.notify_all()
                return True
        return False

    def _release_slot(self, slot, cold=False):
        """Tear down a slot's host + page state (no result recording).
        Paged backend with auto prefix caching: the request's full
        prompt pages are DONATED into the radix tree (future prompts
        sharing the prefix auto-hit them; eviction reclaims them under
        pressure) instead of being freed; everything else — partial
        prompt tail, decode budget — returns to the free list. An
        injected ``prefix.donate`` fault abandons the insert and the
        pages are simply freed: donation is best-effort cache
        maintenance, never a correctness or leak risk. ``cold=True``
        (preemption teardown) donates at the cold end of the LRU so
        the grow that displaced this slot reclaims its pages first."""
        st = self._slots[slot]
        self._active[slot] = False
        self._slots[slot] = None
        if slot in self._prefill_fifo:
            self._prefill_fifo.remove(slot)
        if self._kv is None:
            return
        pages = self._kv.detach_slot(slot)
        if not pages:
            return
        if self._auto_prefix and st is not None:
            try:
                # only prompt rows actually WRITTEN are donated: a slot
                # torn down mid-ragged-prefill (deadline, cancel, fault)
                # caches its filled prefix, never unwritten pages
                n_known = min(st.prompt_len, st.filled)
                new = self._prefix.donate(st.ids, pages, n_known,
                                          cold=cold)
            except Exception:
                self._kv.release(pages)
            else:
                if new and self._tele is not None:
                    self._tele.on_prefix_donate(new)
                if new and self._rec is not None:
                    self._rec.record("donate", rid=st.rid, pages=new,
                                     cold=cold)
        else:
            self._kv.release(pages)

    def _finish_partial_locked(self, slot):
        """Record the slot's partial tokens as its rid's RESULT and tear
        the slot down — the one way a live request leaves early with its
        output kept (cancel, deadline expiry, hard stop). A resumed
        slot's partial is the LONGER of its pre-preemption tokens and
        the replay so far (never fewer tokens than already streamed)."""
        st = self._slots[slot]
        self._results[st.rid] = np.asarray(st.partial()[:st.budget],
                                           np.int32)
        if self._rec is not None:
            self._rec.record("flush", rid=st.rid,
                             tokens=len(self._results[st.rid]))
        if st.journey is not None:
            st.journey.event("flushed",
                             tokens=len(self._results[st.rid]))
        self._release_slot(slot)
        return st

    # ---------------------------------------------------- paged backend
    def _fill_pages(self, caches1, pages, start):
        """Scatter dense batch-1 cache rows [start, start + len(pages) *
        page_size) into the pool at ``pages`` (position order)."""
        if not pages:
            return
        pg = self._kv.page_size
        n = len(pages) * pg
        ids = jnp.asarray(np.asarray(pages, np.int32))

        def seg(c):            # [L, 1, T', h, hd] -> [L, npg, pg, h, hd]
            s = c[:, 0, start:start + n]
            return s.reshape(s.shape[0], len(pages), pg, s.shape[2],
                             s.shape[3])

        pool = jax.tree_util.tree_map(
            lambda p_, c: p_.at[:, ids].set(seg(c).astype(p_.dtype)),
            self._caches["pool"],
            {"k": caches1["k"], "v": caches1["v"]})
        self._caches = dict(self._caches, pool=pool)

    def _seed_from_pages(self, pages):
        """Inverse of ``_fill_pages``: gather cached pool pages back
        into a dense batch-1 cache covering [0, len(pages) *
        page_size) — the auto-hit remainder prefill attends to these
        rows. The decode program reads the SAME pages through the block
        table, so the pool copy stays the single source of truth.
        DENSE prefill mode only: the ragged path attends over cached
        pages through the block table directly, so an auto hit costs
        zero extra dispatches (BENCHNOTES Round 7 measured this
        gather→dense→scatter round-trip exceeding the saved FLOPs on
        small models)."""
        pg = self._kv.page_size
        n = len(pages) * pg
        idx = jnp.asarray(np.asarray(pages, np.int32))
        base = self._init_caches(1)

        def take(pool, dense):         # [L, P, pg, h, hd] -> dense rows
            s = pool[:, idx]
            s = s.reshape(s.shape[0], 1, n, s.shape[3], s.shape[4])
            return dense.at[:, :, :n].set(s.astype(dense.dtype))

        pool = self._caches["pool"]
        if self._costs is not None:    # byte model priced lazily: the
            # pool flatten must not run on the costs=None path
            self._charge_transfer("page_gather",
                                  2 * n * self._row_nbytes())
        return {"k": take(pool["k"], base["k"]),
                "v": take(pool["v"], base["v"])}

    def _spill_payload(self, page):
        """One pool page's K and V rows as host numpy arrays — the
        demotion gather ``PrefixCache.evict`` routes through the host
        tier. On a sharded pool the gather goes PER SHARD: each
        device ships only its kv-head slice (``addressable_shards``,
        ordered by kv-head offset) and the slices concatenate on the
        head dim — never a full-pool replication bounce (the PR-14
        gap). Runs inside an allocator reclaim under the server lock,
        off the tick path."""
        page = int(page)
        out = []
        for name in ("k", "v"):
            leaf = self._caches["pool"][name]
            if self._pool_shards > 1:
                try:
                    shards = sorted(leaf.addressable_shards,
                                    key=lambda s: s.index[3].start or 0)
                    out.append(np.concatenate(
                        [np.asarray(s.data[:, page]) for s in shards],
                        axis=2))
                    continue
                except Exception:
                    pass       # runtime hid the buffers: global gather
            out.append(np.asarray(jax.device_get(leaf[:, page])))
        return out

    def _restore_match(self, m):
        """Restore a tree match's host-resident suffix into freshly
        allocated pool pages so admission can take the WHOLE run by
        reference through the normal ``admit_slot``/refcount path —
        a restored run is bit-exact with a never-evicted one. Returns
        a fresh all-hot ``PrefixMatch`` over the same nodes (possibly
        trimmed to the hot prefix), or None when nothing survives.
        Any failure is a MISS for the affected pages, never a request
        failure: an injected ``tier.restore`` fault leaves the run
        spilled for a later attempt, a checksum mismatch forgets the
        corrupt node (and its all-host subtree) for good, and an
        OutOfPages trims to the hot prefix.

        On a sharded pool the scatter goes PER SHARD: the host
        payload is laid out against the pool's own sharding
        (``jax.device_put`` with the leaf's sharding — each device
        receives only its kv-head slice) before one batched
        ``.at[].set`` — the restore mirror of the spill gather."""
        from .prefix_cache import PrefixMatch
        nodes = m.nodes
        hot = m.hot_len()
        if hot == len(nodes):
            return m
        tele = self._tele
        t0 = tele.restore_started() if tele is not None else None
        payloads, restoring, n_restored = [], [], 0
        for nd in nodes[hot:]:
            try:
                payload = self._host.get(nd.host, fp=nd.fp)
            except Exception:
                break          # transient (injected) miss: run stays
            #                    spilled, nodes intact for retry
            if payload is None:
                # checksum mismatch: the payload is unservable — drop
                # the node and everything under it so the corrupt
                # entry can never be matched again
                if tele is not None:
                    tele.on_host_restore_corrupt()
                if self._rec is not None:
                    self._rec.record("restore_corrupt", fp=nd.fp)
                self._prefix.drop_subtree(nd)
                break
            payloads.append(payload)
            restoring.append(nd)
        if restoring:
            # fresh pages for the suffix: protect the whole run across
            # the alloc — its reclaim sweep must not demote the hot
            # prefix (not yet referenced by a slot) or shrink away the
            # very entries being restored
            self._prefix.protect(nodes[:hot] + restoring)
            try:
                fresh = self._kv.alloc(len(restoring))
            except Exception:
                fresh = None   # pool exhausted even after reclaim:
            finally:           # serve the hot prefix only
                self._prefix.protect(())
            if fresh is not None:
                idx = jnp.asarray(np.asarray(fresh, np.int32))
                pool = dict(self._caches["pool"])
                for j, name in enumerate(("k", "v")):
                    leaf = pool[name]
                    # [L, n, pg, kvh, hd]: page payloads stacked on a
                    # new pages axis, matching leaf[:, idx]
                    val = np.stack([p[j] for p in payloads], axis=1)
                    val = val.astype(leaf.dtype)
                    if self._pool_shards > 1:
                        try:
                            val = jax.device_put(
                                val, leaf.sharding)
                        except Exception:
                            pass
                    pool[name] = leaf.at[:, idx].set(jnp.asarray(val))
                self._caches = dict(self._caches, pool=pool)
                for nd, page in zip(restoring, fresh):
                    self._prefix.promote(nd, page)
                if self._costs is not None:
                    # priced like the gather/scatter detours: bytes
                    # moved both ways, zero FLOPs — and NOT a tick
                    # dispatch (restores must not count against the
                    # megakernel's serving_tick_dispatches profile)
                    self._charge_transfer(
                        "page_restore",
                        2 * len(fresh) * self._kv.page_size
                        * self._row_nbytes())
                if self._rec is not None:
                    self._rec.record("restore", pages=len(fresh))
                n_restored = len(fresh)
                hot += n_restored
        if tele is not None:
            tele.on_host_restore(n_restored, t0)
        if hot == 0:
            return None
        return PrefixMatch(nodes[:hot], self._kv.page_size)

    def _sync_block_table(self):
        """Push the host block-table mirror to the device copy the
        decode program reads. Same shape every time — page churn never
        triggers a recompile."""
        if self._kv is not None and self._kv.dirty:
            self._caches = dict(self._caches,
                                bt=jnp.asarray(self._kv.block_table))
            self._kv.dirty = False
            self._tick_dispatch("block_table")
            self._charge_transfer("block_table",
                                  2 * self._kv.block_table.nbytes)

    def _shard_pool_bytes(self):
        """K+V pool bytes actually RESIDENT on one shard's device —
        measured off the live arrays (an addressable shard's buffer),
        not derived, so a placement bug (pool silently replicated when
        it should shard) shows up as 1x instead of 1/mp. Falls back to
        global bytes / shards where the runtime hides buffers. The pool
        shape and placement are fixed for the server's lifetime, so the
        first measurement is memoized — this rides the per-tick gauge
        path."""
        if self._kv is None:
            return None
        memo = getattr(self, "_shard_bytes_memo", None)
        if memo is not None:
            return memo
        pool = self._caches["pool"]
        try:
            memo = int(pool["k"].addressable_shards[0].data.nbytes
                       + pool["v"].addressable_shards[0].data.nbytes)
        except Exception:
            memo = int((pool["k"].nbytes + pool["v"].nbytes)
                       // max(1, self._pool_shards))
        self._shard_bytes_memo = memo
        return memo

    def _pool_gauges(self):
        """Refresh the page-pool occupancy gauges (paged backend)."""
        if self._tele is not None and self._kv is not None:
            used = self._kv.used_pages()
            pinned = self._prefix.pinned_pages
            cached = self._prefix.cached_pages
            self._tele.set_pool(self._kv.free_pages(),
                                used - pinned - cached, pinned, cached,
                                self._prefix.host_pages)
            self._tele.set_pool_shards(self._pool_shards,
                                       self._shard_pool_bytes())

    def pool_balance(self):
        """``PoolBalance`` — a ``(free, live, pinned, cached)`` tuple
        of page counts summing to the usable pool (``num_pages - 1``;
        page 0 is the null page): ``live`` pages belong to decoding
        slots, ``pinned`` to registered prefixes (never evicted),
        ``cached`` to the auto prefix cache (evictable LRU). Chaos
        suites assert ``live == 0`` once drained — free + pinned +
        cached then covers the whole pool and no injected failure
        leaked a page. Optimistic-admission state rides as ATTRIBUTES
        (``.preempted`` parked requests, ``.preemptions`` cumulative
        victims) so existing 4-way unpacks keep working. Dense backend
        returns None."""
        if self._kv is None:
            return None
        with self._lock:
            free = self._kv.free_pages()
            pinned = self._prefix.pinned_pages
            cached = self._prefix.cached_pages
            live = self._kv.used_pages() - pinned - cached
            shards = self._pool_shards
            per_shard = ()
            if shards > 1:
                # kv-head sharding splits every page across ALL shards
                # equally, so each shard's page counts equal the
                # globals — the view makes that balance assertable
                per_shard = tuple(
                    {"free": free, "live": live, "pinned": pinned,
                     "cached": cached} for _ in range(shards))
            return PoolBalance(free, live, pinned, cached,
                               preempted=len(self._preempted),
                               preemptions=self.stats["preemptions"],
                               num_shards=shards, per_shard=per_shard,
                               shard_page_bytes=self._shard_pool_bytes(),
                               host=self._prefix.host_pages,
                               host_bytes=self._host.bytes_used
                               if self._host is not None else 0)

    def _reclaim_pages(self, shortfall):
        """``PagedKVCache.alloc``'s reclaimer: evict LRU cached prefix
        pages when the free list runs short. An injected
        ``prefix.evict`` fault aborts THIS sweep — alloc then raises
        OutOfPages and admission defers to the next tick; either way
        no page leaks and no request fails. With a host tier the
        sweep DEMOTES instead of dropping: spills are counted (and
        priced — ``page_spill``, 2x bytes moved, never a tick
        dispatch) here by diffing the tier's totals across the sweep,
        so the eviction metrics split into spilled vs dropped."""
        tier = self._host
        s0 = tier.spilled_pages_total if tier is not None else 0
        try:
            freed = self._prefix.evict(shortfall)
        except Exception:
            return 0
        spilled = tier.spilled_pages_total - s0 \
            if tier is not None else 0
        if spilled:
            if self._tele is not None:
                self._tele.on_host_spill(spilled)
            if self._rec is not None:
                self._rec.record("spill", pages=spilled)
            if self._costs is not None:
                self._charge_transfer(
                    "page_spill",
                    2 * spilled * self._kv.page_size
                    * self._row_nbytes())
        dropped = freed - spilled
        if dropped and self._tele is not None:
            self._tele.on_prefix_evict(dropped)
        if freed and self._rec is not None:
            self._rec.record("evict", pages=freed)
        return freed

    def _best_hit(self, ids):
        """The longest reusable prefix state for ``ids``: the
        registered match (dense rows + final logits, token-exact
        length) vs the radix tree's page-aligned cached run — whichever
        covers more tokens. Returns ``("reg", entry)``, ``("tree",
        PrefixMatch)``, or None. A tree match is trimmed page-by-page
        until the remainder's prefill-chunk pad still fits
        ``max_cache_len`` (submit() bound-checked the pad against the
        hits known THEN; the tree moves underneath queued requests),
        and capped one token short of the prompt — the remainder
        prefill must emit the first-token logits.

        RAGGED mode matches through the tree alone: register_prefix
        entries already live in it as pinned nodes, so a registered hit
        reuses its page-aligned run (the sub-page tail re-prefills with
        the remainder — recomputation is deterministic, tokens are
        unchanged) and the stored dense rows are never touched. No
        chunk-pad trim either: ragged remainders never pad."""
        if self._ragged:
            T = int(ids.shape[0])
            tree = self._prefix.lookup(ids, T - 1)
            return None if tree is None else ("tree", tree)
        reg = self._match_prefix(ids)
        best = None if reg is None else ("reg", reg)
        if self._auto_prefix:
            T = int(ids.shape[0])
            tree = self._prefix.lookup(ids, T - 1)
            while tree is not None and \
                    T + self._chunk_pad(T - tree.tokens) \
                    > self.max_cache_len:
                tree = tree.shrink()
            reg_n = reg[0].shape[0] if reg is not None else 0
            if tree is not None and tree.tokens > reg_n:
                best = ("tree", tree)
        return best

    def _pinned_run_pages(self, ids):
        """Pages of the PINNED (register_prefix) tree run this prompt
        would share — the stable floor on page reuse a ragged-mode
        submit may count (capped at T-1 like ``_best_hit``'s lookup, so
        the remainder prefill keeps its first-token row)."""
        T = int(ids.shape[0])
        aligned = (T - 1) // self._kv.page_size * self._kv.page_size
        n = 0
        for nd in self._prefix.node_run(ids[:aligned]):
            if not nd.pinned:
                break
            n += 1
        return n

    def _request_pages(self, ids, budget, hit):
        """Fresh pages a request needs for its FULL extent (prompt +
        budget — reserved at admission so decode-time growth can never
        hit an empty pool mid-flight), net of the shared pages of
        ``hit`` (the caller's ``_match_prefix`` result)."""
        shared = len(hit[3]) if hit is not None else 0
        return self._npages_for(ids.shape[0] + budget) - shared

    def _extent_tokens(self, T, budget):
        """Tokens' worth of pages admission reserves for a request.
        ``admission="reserve"``: the FULL extent (prompt + budget), so
        decode can never hit an empty pool mid-flight.
        ``"optimistic"``: the prompt plus ``headroom_pages`` worth —
        decode grows page-by-page on demand (``_grow_locked``) and the
        preemption policy settles the bill when the gamble loses."""
        if self._optimistic:
            return min(T + self._headroom_pages * self.page_size,
                       T + budget)
        return T + budget

    def _head_fits_pool(self, head, best):
        """Can the pool admit ``head`` (the chosen admission candidate)
        right now? If not it (and everything behind it in admission
        order) waits for a harvest to free pages. Evictable
        prefix-cache pages count as available headroom (alloc reclaims
        them on demand) — minus the nodes the head's own cache hit
        (``best``, computed once per admission attempt and shared with
        the admit) is about to take by reference, which obviously
        cannot be evicted to make room for it. Optimistic admission
        only asks for the prompt + headroom reservation here."""
        if best is None:
            shared, nodes = 0, ()
        elif best[0] == "reg":
            shared, nodes = len(best[1][3]), ()
        else:
            # only the HOT prefix is shared by reference; a
            # host-resident suffix needs fresh pool pages (the restore
            # allocates them before admit_slot), so it counts toward
            # need exactly like prefilling those tokens would
            hot = best[1].hot_len()
            shared, nodes = hot, best[1].nodes[:hot]
        need = self._npages_for(
            self._extent_tokens(head.ids.shape[0], head.budget)) - shared
        avail = self._kv.free_pages() \
            + self._prefix.evictable_pages(exclude=nodes)
        return avail >= need

    def _npages_for(self, n_tokens):
        return -(-int(n_tokens) // self._kv.page_size)

    def _skipped_dma(self, live_tokens):
        """The goodput ledger's host-side MODEL of one slot's masked
        page traffic in one kernel launch UNDER ``serving_mode=
        "split"``: the split kernels' grid covers the full block-table
        width, so every page wholly beyond the slot's live length is
        DMAed but masked (PR-6 known cut) — ``(table_width -
        ceil(live/pg)) * pg`` token-equivalents; this is the ONE
        definition both the split decode and prefill hooks charge.
        ``serving_mode="fused"`` (ISSUE 14) lifted the cut: its DMA
        schedule covers only live pages, so ``_step_fused`` never
        calls this — the only masked DMA it charges is the schedule's
        pow2-ladder pad."""
        live = -(-int(live_tokens) // self.page_size)
        return max(0, self._bt_pages - live) * self.page_size

    # -------------------------------------------- admission scheduling
    def _next_admission_locked(self):
        """``(item, source)`` of the next admission candidate, or
        ``(None, None)``. Reserve mode: strict FIFO — the queue head.
        Optimistic mode: PRIORITY-AWARE FIFO — highest priority class
        first, then original submit order (rid), in one order across
        the preempted queue and the main queue; a preempted request
        keeps its original rid, so at equal priority it re-enters
        ahead of later arrivals. ``source`` is the pop/defer handle."""
        if not self._optimistic \
                or (not self._priority_seen and not self._preempted):
            # reserve mode, or optimistic with every priority at the
            # default and nothing parked: the priority-aware order IS
            # rid order, so skip the O(queue) scan per admission (the
            # common case keeps the reserve path's O(1) head peek)
            if not self._queue:
                return None, None
            return self._queue[0], ("queue", 0)
        best, src = None, None
        for where, items in (("queue", self._queue),
                             ("preempted", self._preempted)):
            for i, item in enumerate(items):
                if best is None or (-item.priority, item.rid) \
                        < (-best.priority, best.rid):
                    best, src = item, (where, i)
        return best, src

    def _pop_admission_locked(self, src):
        where, i = src
        items = self._queue if where == "queue" else self._preempted
        item = items.pop(i)
        if where == "preempted":
            self._preempt_gauge()
        return item

    def _defer_admission_locked(self, src, item):
        """Put a popped candidate back where it came from (an admission
        attempt rolled back — OutOfPages defer)."""
        where, i = src
        (self._queue if where == "queue"
         else self._preempted).insert(i, item)
        if where == "preempted":
            self._preempt_gauge()

    def _preempt_gauge(self):
        if self._tele is not None:
            self._tele.set_preempted_depth(len(self._preempted))

    def _flush_parked_locked(self, rec):
        """Record a parked record's pre-preemption partial as its
        rid's RESULT — the one way a preempted request leaves the
        parked queue without decode resuming (cancel, deadline expiry,
        hard stop, dead-replica evacuation). The caller removes the
        record from ``_preempted`` and handles telemetry/notify."""
        self._results[rec.rid] = np.asarray(rec.emitted[:rec.budget],
                                            np.int32)
        if self._rec is not None:
            self._rec.record("flush", rid=rec.rid,
                             tokens=len(self._results[rec.rid]),
                             parked=True)
        if rec.journey is not None:
            rec.journey.event("flushed",
                              tokens=len(self._results[rec.rid]),
                              parked=True)

    # ------------------------------------------------------- scheduling
    def _admit(self, run_prefill=True):
        """Fill free slots from the queue. Dense prefill mode: one
        dense batch-1 prefill program per admission (the PR-5 path).
        Ragged mode: admissions only RESERVE their slot + full page
        extent here (cheap, host-side); the actual prompt chunks run
        batched in ``_prefill_tick`` — several admissions, one launch,
        straight into pool pages — interleaved with decode under the
        per-tick token budget. A request whose admission raises is
        recorded in ``_failures`` (its waiters get the error) instead
        of killing the serve thread or losing the rest of the queue
        (ADVICE r5 #2)."""
        if self._ragged:
            self._admit_ragged(run_prefill)
            return
        admitted = 0
        for slot in range(self.max_slots):
            if self._slots[slot] is not None:
                continue
            if self._admit_cap is not None and admitted >= self._admit_cap:
                break
            item, src = self._next_admission_locked()
            if item is None:
                break
            # one _best_hit per admission attempt: the radix walk (and
            # registered-prefix scan) feeds the fits check AND the
            # admission itself — same lock, same tick, the tree cannot
            # move between the two
            best = self._best_hit(item.ids)
            if self._kv is not None \
                    and not self._head_fits_pool(item, best):
                break
            req = self._pop_admission_locked(src)
            rid = req.rid
            if self._tele is not None:
                self._tele.on_admit(rid, len(self._queue))
            try:
                self._admit_one(slot, req, best)
            except OutOfPages:
                # eviction could not free enough right now (an injected
                # ``prefix.evict`` fault aborted the sweep, or a cache
                # hit shrank the headroom mid-admission): roll back and
                # DEFER — the request returns to the head of the queue
                # (FIFO preserved) and is retried next tick, it does
                # NOT fail
                if self._kv is not None and self._kv.slot_pages(slot):
                    self._kv.free_slot(slot)
                self._active[slot] = False
                self._slots[slot] = None
                self._defer_admission_locked(src, req)
                if self._tele is not None:
                    self._tele.on_admission_deferred(rid,
                                                     len(self._queue))
                if self._rec is not None:
                    self._rec.record("defer", rid=rid)
                if req.journey is not None:
                    req.journey.event("deferred")
                break
            except Exception as e:
                if self._kv is not None and self._kv.slot_pages(slot):
                    self._kv.free_slot(slot)     # roll back a part-admit
                self._active[slot] = False
                self._slots[slot] = None
                self._failures[rid] = e
                if self._tele is not None:
                    self._tele.on_admission_failure(rid, e)
                self._note_request_failure_locked(rid, e, req.journey)
                self._done_cv.notify_all()
            else:
                admitted += 1
        if self._tele is not None:
            self._pool_gauges()

    def _admit_ragged(self, run_prefill=True):
        """Ragged-mode scheduling pass: pop queued requests into free
        slots (reservation only — ``admit_slot`` takes the full
        prompt + budget extent, shared cache-hit pages by reference),
        then run one batched ragged prefill launch over every slot with
        prompt rows still to write. OutOfPages DEFERS the head request
        exactly like the dense path; nothing is prefilled for a
        deferred reservation, so counters see each admission once."""
        admitted = 0
        for slot in range(self.max_slots):
            if self._admit_cap is not None and admitted >= self._admit_cap:
                break
            if self._slots[slot] is not None:
                continue
            item, src = self._next_admission_locked()
            if item is None:
                break
            best = self._best_hit(item.ids)
            if not self._head_fits_pool(item, best):
                break
            req = self._pop_admission_locked(src)
            if self._tele is not None:
                self._tele.on_admit(req.rid, len(self._queue))
            try:
                self._reserve_one(slot, req, best)
            except OutOfPages:
                # eviction could not free enough right now (an injected
                # ``prefix.evict`` fault aborted the sweep): the request
                # returns to the head of the queue (FIFO preserved) and
                # is retried next tick — admit_slot rolled its own
                # shared-page refs back, nothing was prefilled
                self._defer_admission_locked(src, req)
                if self._tele is not None:
                    self._tele.on_admission_deferred(req.rid,
                                                     len(self._queue))
                if self._rec is not None:
                    self._rec.record("defer", rid=req.rid)
                if req.journey is not None:
                    req.journey.event("deferred")
                break
            except Exception as e:
                if self._kv.slot_pages(slot):
                    self._kv.free_slot(slot)     # roll back a part-admit
                self._active[slot] = False
                self._slots[slot] = None
                if slot in self._prefill_fifo:
                    self._prefill_fifo.remove(slot)
                self._failures[req.rid] = e
                if self._tele is not None:
                    self._tele.on_admission_failure(req.rid, e)
                self._note_request_failure_locked(req.rid, e,
                                                  req.journey)
                self._done_cv.notify_all()
            else:
                admitted += 1
        if run_prefill:
            self._prefill_tick()
        if self._tele is not None:
            self._pool_gauges()

    def _reserve_one(self, slot, req, best):
        """Reserve ``slot`` for ``req``: full-extent page reservation
        (prompt + budget, cache-hit pages joined by reference) and a
        prefill-phase slot record. No device work happens here — the
        prompt's chunks run in ``_prefill_tick`` launches."""
        if self._faults is not None:
            # chaos failure point: an admission that dies is a
            # PER-REQUEST failure (_admit_ragged records it), never a
            # server one — and it fires BEFORE the reservation, so no
            # pages need rolling back
            self._faults.check(faults.PREFILL, rid=req.rid)
        ids = req.ids
        T = ids.shape[0]
        if best is not None and best[0] == "tree" \
                and self._host is not None:
            # the match may carry a host-resident suffix: restore it
            # into fresh pool pages FIRST so admit_slot below shares
            # the whole run by reference like any hot hit (a failed
            # restore just trims the match — prefill covers the rest)
            m = self._restore_match(best[1])
            best = None if m is None else ("tree", m)
        if best is not None:
            m = best[1]
            n_pre, pre_pages = m.tokens, m.pages
        else:
            m, n_pre, pre_pages = None, 0, []
        self._kv.admit_slot(slot, self._extent_tokens(T, req.budget),
                            pre_pages)
        self._count_headroom(slot, T)
        if m is not None:
            self._prefix.use(m)               # LRU: reuse is recency
            # attribution: pinned nodes are register_prefix state (the
            # run's head — extend_pinned pins whole root paths), the
            # unpinned tail is the automatic cache's
            n_auto = n_pre - sum(1 for nd in m.nodes if nd.pinned) \
                * self._kv.page_size
        else:
            n_auto = 0
        self.stats["prefix_hit_tokens"] += n_pre
        if n_auto:
            self.stats["prefix_auto_hits"] += 1
            self.stats["prefix_auto_hit_tokens"] += n_auto
        if self._tele is not None and self._auto_prefix:
            self._tele.on_prefix_auto(n_auto > 0, n_auto)
        st = _Slot(req.rid, ids, T, req.budget, req.on_token,
                   req.deadline)
        st.phase = "prefill"
        st.fill_pos = st.filled = n_pre
        st.n_pre = n_pre
        st.seed = req.seed
        if self._led is not None:
            # ragged matching is page-granular, so a registered
            # prefix's sub-page tail re-prefills with the remainder —
            # the ledger's tail_reprefill kind. The longest registered
            # match decides; rows below reprefill_upto that the prefill
            # launches are recomputation of registered state
            reg = self._match_prefix(ids)
            if reg is not None and reg[0].shape[0] > n_pre:
                st.reprefill_upto = int(reg[0].shape[0])
        self._bind_request(st, req, slot)
        self._slots[slot] = st
        self._prefill_fifo.append(slot)
        if not self._fused:
            # park the slot's decode write position past the block
            # table: until activation, its wasted decode-step writes
            # null-redirect (zeroed) instead of corrupting the pages
            # being prefilled. (Fused mode has no device-resident slot
            # state to park — mid-prefill slots ride the launch as
            # real prefill rows, idle ones are kernel-skipped.)
            self._pending_t[slot] = self.max_cache_len

    def _bind_request(self, st, req, slot):
        """Carry the request's scheduling state onto its slot. A
        RESUMED (previously preempted) request keeps its stream offset
        (on_token never re-sends delivered chunks — the replay is
        bit-identical below it), its pre-preemption partial (flushed if
        it must leave early again), and its preemption count. Also the
        observability funnel for admissions: one flight-recorder event
        and one journey phase per (re)admission, ``replay`` when the
        request came off the preempted queue."""
        st.priority = req.priority
        st.journey = req.journey
        resumed = isinstance(req, _Preempted)
        if resumed:
            st.streamed = req.streamed
            st.replayed = tuple(req.emitted)
            st.preempts = req.preempts
            self.stats["preempt_resumed"] += 1
            if self._tele is not None:
                self._tele.on_preempt_resumed()
        if self._rec is not None:
            self._rec.record("replay" if resumed else "admit",
                             rid=st.rid, slot=slot,
                             prompt=st.prompt_len, prefix_hit=st.n_pre)
        if st.journey is not None:
            st.journey.event("replay" if resumed else "admitted",
                             slot=slot, prefix_hit=st.n_pre)

    def _count_headroom(self, slot, T):
        """Account the pages an optimistic admission reserved BEYOND
        the prompt (its pre-paid growth headroom)."""
        if not self._optimistic:
            return
        hr = len(self._kv.slot_pages(slot)) - self._npages_for(T)
        if hr > 0:
            self.stats["headroom_pages"] += hr
            if self._tele is not None:
                self._tele.add_headroom_pages(hr)

    def _prefill_tick(self):
        """Run one batched ragged prefill launch: the next chunk of
        every mid-prefill slot (head-of-FIFO first — Sarathi-style, the
        oldest admission completes soonest), bounded by the per-tick
        token budget so a long prompt cannot stall in-flight decode
        ticks. Chunk width C is padded up a power-of-two ladder (min 2:
        single-row matmuls take XLA's fused-reduce path and break
        bit-parity with the dense prefill) so compiles stay
        O(log max_cache_len)."""
        budget = self._prefill_budget - self._prefill_used
        if not self._prefill_fifo or budget <= 0:
            return
        plan = []                        # (slot, start, take)
        used = 0
        for slot in self._prefill_fifo:
            if used >= budget:
                break
            st = self._slots[slot]
            take = min(st.prompt_len - st.fill_pos, budget - used)
            plan.append((slot, st.fill_pos, take))
            used += take
        if not plan:
            return
        self._prefill_used += used
        C = max(2, 1 << (max(t for _, _, t in plan) - 1).bit_length())
        S = self.max_slots
        toks = np.zeros((S, C), np.int32)
        t0 = np.full((S,), self.max_cache_len, np.int32)  # idle sentinel
        out_idx = np.zeros((S,), np.int32)
        done = []
        for slot, start, take in plan:
            st = self._slots[slot]
            toks[slot, :take] = st.ids[start:start + take]
            t0[slot] = start
            if start + take == st.prompt_len:
                out_idx[slot] = take - 1
                done.append(slot)
        self._sync_block_table()
        tele = self._tele
        t_started = tele.prefill_started() if tele is not None else None
        if self._phase_timer is not None:
            self._phase_timer.mark("admission")
        wall0 = _time_mod.perf_counter()
        toks_d, t0_d, out_d = (jnp.asarray(toks), jnp.asarray(t0),
                               jnp.asarray(out_idx))
        prefill_fn = self._ragged_fn
        if self._costs is not None:
            # one priced program per chunk width on the pow2 ladder —
            # a width first seen AFTER warmup is exactly the recompile
            # the watch exists to surface
            prefill_fn = self._cost_program(
                self._cost_op("prefill"), self._ragged_fn,
                (toks_d, t0_d, self._caches, out_d))
        logits, self._caches = prefill_fn(toks_d, t0_d, self._caches,
                                          out_d)
        self._count_dispatches(1, op="prefill")
        led = self._led
        for slot, start, take in plan:
            st = self._slots[slot]
            st.fill_pos = st.filled = start + take
            self.stats["prefill_tokens"] += take
            if led is not None:
                # the launch runs C query rows for each participating
                # slot (idle slots are kernel-skipped): `take` real
                # rows + pow2-ladder pad, and maxp page DMAs of which
                # only the covered prefix is unmasked
                if st.preempts:
                    # a resumed request's prompt re-prefill is pure
                    # preemption recompute, whatever rows it covers
                    led.add("replay", take)
                else:
                    tail = max(0, min(start + take,
                                      st.reprefill_upto) - start)
                    led.add("tail_reprefill", tail)
                    led.add("goodput", take - tail)
                led.add("chunk_pad", C - take)
                led.add("skipped_page_dma",
                        self._skipped_dma(start + take))
            if st.journey is not None:
                st.journey.event("prefill_chunk", start=start,
                                 take=take)
        for slot in done:
            self._activate(slot, logits[slot:slot + 1])
        self.stats["prefill_wall_s"] += _time_mod.perf_counter() - wall0
        if self._phase_timer is not None:
            self._phase_timer.mark("prefill_launch")
        if tele is not None:
            tele.on_prefill_batch(t_started, used)

    def _activate(self, slot, logits):
        """A slot's prompt is fully written: draw its first token from
        the ragged launch's logits row (same PRNG chain and logit ops
        as the dense path — bit-identical draws) and flip it into the
        decode phase."""
        st = self._slots[slot]
        key = jax.random.PRNGKey(st.seed)
        if self.do_sample:
            # same split pattern as sample_generate.run: one split,
            # sample tok0 from the [1, V] prefill logits row
            key, sub = jax.random.split(key)
            from .decode_loop import process_logits
            first = int(jax.random.categorical(
                sub, process_logits(logits, self._temperature,
                                    self._top_k, self._top_p),
                axis=-1)[0])
        else:
            first = int(jnp.argmax(logits, -1)[0])
        self._pending_key[slot] = key
        self._pending_tok[slot] = first
        self._pending_t[slot] = st.prompt_len
        st.phase = "decode"
        self._active[slot] = True
        self._prefill_fifo.remove(slot)
        st.emitted.append(first)
        if st.journey is not None:
            st.journey.event("first_token")
        st.stream(self._deferred_cbs)
        self.stats["admissions"] += 1
        if self._tele is not None:
            self._tele.on_first_token(st.rid, st.prompt_len - st.n_pre,
                                      st.n_pre)

    def _flush_slot_state(self):
        """Push pending per-slot decode state (first token, write
        position, PRNG key) to the device arrays the decode program
        consumes — ONE batched update per array per tick instead of
        three dispatches per admission."""
        if self._pending_tok:
            idx = jnp.asarray(list(self._pending_tok), jnp.int32)
            vals = jnp.asarray(list(self._pending_tok.values()),
                               jnp.int32)
            self._tok = self._tok.at[idx].set(vals)
            self._pending_tok.clear()
            self._count_dispatches(1, op="state_push")
            self._charge_transfer("state_push", 2 * self._tok.nbytes)
        if self._pending_t:
            idx = jnp.asarray(list(self._pending_t), jnp.int32)
            vals = jnp.asarray(list(self._pending_t.values()), jnp.int32)
            self._t = self._t.at[idx].set(vals)
            self._pending_t.clear()
            self._count_dispatches(1, op="state_push")
            self._charge_transfer("state_push", 2 * self._t.nbytes)
        if self._pending_key:
            idx = jnp.asarray(list(self._pending_key), jnp.int32)
            vals = jnp.stack(list(self._pending_key.values()))
            self._keys = self._keys.at[idx].set(vals)
            self._pending_key.clear()
            self._count_dispatches(1, op="state_push")
            self._charge_transfer("state_push", 2 * self._keys.nbytes)

    def _count_dispatches(self, n=1, op="prefill"):
        """Account ``n`` host->device dispatches on the admission/
        prefill path (prefill program launches, page gathers/scatters,
        slot-state pushes) — the counter-asserted signal that the
        ragged path eliminated the per-admission detour. ``op`` labels
        the dispatch in this tick's profile (the item-4 baseline)."""
        self.stats["prefill_dispatches"] += n
        self._tick_disp[op] = self._tick_disp.get(op, 0) + n
        if self._tele is not None:
            self._tele.add_prefill_dispatches(n)

    def _tick_dispatch(self, op, n=1):
        """Account ``n`` dispatches that are NOT admission/prefill work
        (the decode program itself, block-table syncs) in this tick's
        per-op profile only."""
        self._tick_disp[op] = self._tick_disp.get(op, 0) + n

    def _cost_op(self, name):
        """Cost-catalog op name for a serving program: suffixed with
        the pool shard count on a mesh (``decode_mp4``) so a catalog
        SHARED across servers at different mp never sees one op's
        shape signature change — a warmed op's new signature is
        exactly what the post-warmup recompile alarm fires on, and a
        mesh size is a deployment choice, not a recompile. Unsharded
        servers keep the bare names (dashboards unchanged)."""
        return name if self._pool_shards <= 1 \
            else f"{name}_mp{self._pool_shards}"

    def _cost_program(self, op, fn, args):
        """The cost catalog's priced executable for ``fn`` at ``args``'
        shape signature (compiled + priced on first sight; calling it
        dispatches AND charges). The compile-watch funnel lives here: a
        fresh compile lands a ``compile`` recorder event, and one that
        happens AFTER the catalog warmed is a RECOMPILE — flagged on
        the event and stamped as a ``compile_stall`` journey phase on
        every request parked behind the stalled tick (queued, mid-
        prefill, live slots, preempted), so the latency spike those
        requests see is attributable to XLA. Caller guarantees
        ``self._costs is not None``."""
        prog = self._costs.program(op, fn, args)
        if getattr(prog, "compiled_now", False):
            if self._rec is not None:
                self._rec.record("compile", op=op,
                                 recompile=prog.recompile,
                                 seconds=prog.compile_s)
            if prog.recompile:
                stalled = [item.journey for item in self._queue]
                stalled += [rec.journey for rec in self._preempted]
                stalled += [st.journey for st in self._slots
                            if st is not None]
                for journey in stalled:
                    if journey is not None:
                        journey.event("compile_stall", op=op)
        return prog

    def _charge_transfer(self, op, nbytes):
        """Price a host<->device data movement that is not a compiled
        program (slot-state push, page gather/scatter, block-table
        sync): bytes moved — read + write of the touched buffers —
        zero FLOPs. No-op without an enabled cost catalog."""
        if self._costs is not None:
            self._costs.charge_bytes(op, int(nbytes))

    def _row_nbytes(self):
        """Bytes one token's K+V rows occupy across every layer of the
        page pool — the unit the page gather/scatter transfer charges
        are priced in. Computed once from the pool leaves."""
        if self._kv_row_nbytes is None:
            pool = self._caches["pool"]
            pg = self._kv.page_size
            self._kv_row_nbytes = sum(
                leaf.nbytes // (leaf.shape[1] * pg)
                for leaf in jax.tree_util.tree_leaves(pool))
        return self._kv_row_nbytes

    def _n_prefill_calls(self, seg_len):
        """Dense-prefill program launches ``_run_prefill`` makes for a
        ``seg_len``-token segment (1 unchunked, else one per chunk)."""
        if seg_len <= 0:
            return 0
        c = self._prefill_chunk
        if not c or seg_len <= c:
            return 1
        return (seg_len + self._chunk_pad(seg_len)) // c

    def _admit_one(self, slot, req, best=None):
        rid, ids, budget = req.rid, req.ids, req.budget
        req_seed, on_token, deadline = req.seed, req.on_token, req.deadline
        if self._faults is not None:
            # chaos failure point: an admission prefill that dies is a
            # PER-REQUEST failure (_admit records it), never a server one
            self._faults.check(faults.PREFILL, rid=rid)
        T = ids.shape[0]
        # per-request prefill at batch 1 (optionally in fixed-size
        # chunks: one compiled program for every prompt length),
        # then scatter into the pool. A registered-prefix hit seeds
        # the caches from the stored dense rows; an AUTOMATIC
        # prefix-cache hit (radix tree over donated pages) gathers the
        # cached pages back into a dense batch-1 cache — either way
        # only the remainder is prefilled.
        if best is None:
            best = self._best_hit(ids)
        if best is not None and best[0] == "tree" \
                and self._host is not None:
            # restore any host-resident suffix before the pages are
            # shared/gathered below (dense path mirror of the ragged
            # _reserve_one wiring)
            m2 = self._restore_match(best[1])
            best = None if m2 is None else ("tree", m2)
        if best is not None and best[0] == "tree":
            n_pre, pre_pages = best[1].tokens, best[1].pages
        elif best is not None:
            n_pre, pre_pages = best[1][0].shape[0], best[1][3]
        else:
            n_pre, pre_pages = 0, []
        own = []
        if self._kv is not None:
            # reserve the slot's FULL extent (prompt + budget) before
            # any prefill work or stats: an OutOfPages here (aborted
            # eviction sweep, headroom shrunk mid-tick) defers the
            # request with no prefill wasted and nothing counted — the
            # retry starts from zero, so counters see each admission
            # ONCE. Shared cache-hit pages join the slot's table by
            # reference and are referenced before the alloc, so its
            # reclaim sweep can never evict them; mid-decode growth can
            # never exhaust the pool. (Optimistic admission reserves
            # only prompt + headroom here; _grow_locked pays as it goes.)
            own = self._kv.admit_slot(slot,
                                      self._extent_tokens(T, budget),
                                      pre_pages)
            self._count_headroom(slot, T)
        tele = self._tele
        t_started = tele.prefill_started() if tele is not None else None
        if self._phase_timer is not None:
            self._phase_timer.mark("admission")
        wall0 = _time_mod.perf_counter()

        def _ledger_prefill(n_seg):
            # dense-path prefill rows: n_seg real rows (replay when a
            # preempted request re-prefills its prompt) + the chunked
            # prefill's remainder pad. The dense program runs on dense
            # batch-1 caches — no page DMAs to model here.
            if self._led is not None and n_seg:
                self._led.add("replay" if isinstance(req, _Preempted)
                              else "goodput", n_seg)
                self._led.add("chunk_pad", self._chunk_pad(n_seg))

        if best is not None and best[0] == "tree":
            m = best[1]
            self._prefix.use(m)               # LRU: reuse is recency
            caches1 = self._seed_from_pages(m.pages)
            self._count_dispatches(1, op="page_gather")   # the detour
            rest = ids[n_pre:]                # never empty (lookup cap)
            self.stats["prefix_hit_tokens"] += n_pre
            self.stats["prefix_auto_hits"] += 1
            self.stats["prefix_auto_hit_tokens"] += n_pre
            logits, caches1 = self.model._run_prefill(
                self._bundle, rest[None], chunk=self._prefill_chunk,
                caches=caches1, t0=n_pre)
            self._count_dispatches(self._n_prefill_calls(rest.shape[0]))
            self.stats["prefill_tokens"] += rest.shape[0]
            _ledger_prefill(rest.shape[0])
            if tele is not None:
                tele.on_prefix_auto(True, n_pre)
        elif best is not None:
            rows, pre_logits = best[1][1], best[1][2]
            caches1 = jax.tree_util.tree_map(
                lambda full, r: full.at[:, :, :r.shape[2]].set(r),
                self._init_caches(1), rows)
            self._count_dispatches(1, op="page_scatter")  # dense-row seed
            if self._costs is not None:    # byte model priced lazily:
                # the tree flatten must not run on the costs=None path
                self._charge_transfer(
                    "page_scatter",
                    2 * sum(leaf.nbytes for leaf
                            in jax.tree_util.tree_leaves(rows)))
            rest = ids[n_pre:]
            self.stats["prefix_hit_tokens"] += n_pre
            if rest.shape[0]:
                logits, caches1 = self.model._run_prefill(
                    self._bundle, rest[None],
                    chunk=self._prefill_chunk, caches=caches1, t0=n_pre)
                self._count_dispatches(
                    self._n_prefill_calls(rest.shape[0]))
                self.stats["prefill_tokens"] += rest.shape[0]
                _ledger_prefill(rest.shape[0])
            else:
                logits = pre_logits
            if tele is not None and self._auto_prefix:
                tele.on_prefix_auto(False, 0)
        else:
            logits, caches1 = self.model._run_prefill(
                self._bundle, ids[None], chunk=self._prefill_chunk)
            self._count_dispatches(self._n_prefill_calls(T))
            self.stats["prefill_tokens"] += T
            _ledger_prefill(T)
            if tele is not None and self._auto_prefix:
                tele.on_prefix_auto(False, 0)
        key = jax.random.PRNGKey(req_seed)
        if self.do_sample:
            # same split pattern as sample_generate.run: one split,
            # sample tok0 from the [1, V] prefill logits
            key, sub = jax.random.split(key)
            from .decode_loop import process_logits
            first = int(jax.random.categorical(
                sub, process_logits(logits, self._temperature,
                                    self._top_k, self._top_p),
                axis=-1)[0])
        else:
            first = int(jnp.argmax(logits, -1)[0])
        self._keys = self._keys.at[slot].set(key)
        if self._kv is not None:
            # only prompt rows are copied into the reserved pages; the
            # shared prefix pages ahead of them are already filled
            pg = self._kv.page_size
            n_prompt = -(-T // pg) - len(pre_pages)
            if own[:n_prompt]:
                self._count_dispatches(1, op="page_scatter")  # remainder pages
                if self._costs is not None:
                    # charged HERE, not inside _fill_pages: the other
                    # _fill_pages caller is register_prefix, which
                    # stays off the cost ledger like it stays off
                    # goodput
                    self._charge_transfer(
                        "page_scatter",
                        2 * len(own[:n_prompt]) * pg
                        * self._row_nbytes())
            self._fill_pages(caches1, own[:n_prompt],
                             len(pre_pages) * pg)
        else:
            self._caches = jax.tree_util.tree_map(
                lambda pool, one: pool.at[:, slot].set(one[:, 0]),
                self._caches, caches1)
            self._count_dispatches(1, op="page_scatter")  # dense row copy
            if self._costs is not None:
                self._charge_transfer(
                    "page_scatter",
                    2 * sum(leaf.nbytes for leaf
                            in jax.tree_util.tree_leaves(caches1)))
        self._tok = self._tok.at[slot].set(first)
        self._t = self._t.at[slot].set(T)
        self._count_dispatches(3, op="state_push")    # tok/t/key pushes
        if self._costs is not None:
            # three transfers, charged as three — the cost ledger's
            # dispatch count must reconcile 1:1 with the tick profile
            self._charge_transfer("state_push", 2 * self._tok.nbytes)
            self._charge_transfer("state_push", 2 * self._t.nbytes)
            self._charge_transfer("state_push", 2 * self._keys.nbytes)
        self._active[slot] = True
        st = _Slot(rid, ids, T, budget, on_token, deadline)
        st.n_pre = n_pre
        st.seed = req_seed
        self._bind_request(st, req, slot)
        st.emitted.append(int(first))
        if st.journey is not None:
            st.journey.event("first_token")
        st.stream(self._deferred_cbs)
        self._slots[slot] = st
        self.stats["admissions"] += 1
        self.stats["prefill_wall_s"] += _time_mod.perf_counter() - wall0
        if self._phase_timer is not None:
            self._phase_timer.mark("prefill_launch")
        if tele is not None:
            tele.on_prefill_batch(t_started, T - n_pre)
            tele.on_first_token(rid, T - n_pre, n_pre)

    # ------------------------------------- optimistic growth / preemption
    def _grow_locked(self):
        """Optimistic admission's per-tick growth pass: every active
        slot whose next ``tick_block`` decode writes would cross its
        block-table coverage gets pages appended ON DEMAND
        (``PagedKVCache.grow_slot``); when the pool cannot supply them
        the preemption policy frees victims (``_grow_one_locked``).
        Runs under the server lock BEFORE the decode dispatch, so the
        device program always sees tables covering every row it will
        genuinely need — rows past a request's total extent
        null-redirect harmlessly, exactly like reserve mode's wasted
        block steps."""
        n = self.tick_block
        for slot in range(self.max_slots):
            if not self._active[slot]:
                continue              # empty, mid-prefill, or just parked
            st = self._slots[slot]
            # next tick writes rows [t, t + n), t = prompt_len +
            # emitted - 1; rows at or past prompt + budget are never
            # read back (harvest stops the slot first)
            needed = min(st.prompt_len + len(st.emitted) - 1 + n,
                         st.prompt_len + st.budget)
            try:
                self._grow_one_locked(slot, st, needed)
            except PreemptedError:
                # the grower itself ranked last and was parked — typed,
                # internal, and caught HERE: it never reaches a waiter
                continue

    def _grow_one_locked(self, slot, st, needed_tokens):
        """Grow one slot to cover ``needed_tokens``, preempting victims
        if the pool is genuinely exhausted. Loop invariant: every
        iteration either succeeds, raises (transient tick failure —
        retried by the supervisor with all state consistent), or
        removes one live slot from the candidate set, so it terminates;
        when the grower itself is the least valuable live work it parks
        itself (``PreemptedError``, caught by ``_grow_locked``) rather
        than evict anyone ranked above it."""
        kv = self._kv
        need = self._npages_for(needed_tokens) - len(kv.slot_pages(slot))
        if need <= 0:
            return
        while True:
            try:
                kv.grow_slot(slot, need)
            except OutOfPages:
                if kv.free_pages() \
                        + self._prefix.evictable_pages() >= need:
                    # pages exist but this reclaim sweep died (injected
                    # ``prefix.evict`` fault): a TRANSIENT tick failure
                    # — the supervisor retries; preempting here would
                    # burn a victim for pages already reclaimable
                    raise
                cands = [(s, self._slots[s])
                         for s in range(self.max_slots)
                         if self._slots[s] is not None]
                victim = self._preempt_policy.pick(slot, cands)
                if victim is None:
                    raise      # no live work to free: genuine exhaustion
                if self._faults is not None:
                    # chaos point: an aborted victim teardown leaves the
                    # victim decoding and fails the TICK (supervised
                    # retry); victims already parked this sweep stay
                    # safely parked — nothing leaks either way
                    self._faults.check(faults.SERVER_PREEMPT,
                                       slot=victim, grower=slot,
                                       rid=self._slots[victim].rid)
                if victim == slot:
                    self._preempt_slot_locked(slot)
                    raise PreemptedError(
                        f"request {st.rid} parked by its own page "
                        f"growth (least valuable live work)")
                self._preempt_slot_locked(victim)
            else:
                self.stats["grow_pages"] += need
                if self._tele is not None:
                    self._tele.add_grow_pages(need)
                if self._rec is not None:
                    self._rec.record("grow", rid=st.rid, slot=slot,
                                     pages=need)
                if st.journey is not None:
                    st.journey.event("grow", pages=need)
                return

    def _preempt_slot_locked(self, slot):
        """Tear a victim down BIT-EXACTLY resumable: park its replay
        record (resolved seed, absolute deadline, stream offset, the
        partial so far) on the preempted queue, donate its written
        prompt prefix pages into the radix tree COLD (the triggering
        grow reclaims them first; a quick re-admission still auto-hits
        whatever survives), and free the rest. The waiter keeps
        blocking: re-admission replays the identical token chain —
        greedy trivially, sampled because the chain restarts from the
        same resolved seed through the same programs."""
        st = self._slots[slot]
        rec = _Preempted(st)
        if self._rec is not None:
            self._rec.record("preempt", rid=st.rid, slot=slot,
                             tokens=len(rec.emitted),
                             preempts=rec.preempts)
        if st.journey is not None:
            st.journey.event("preempted", slot=slot,
                             tokens=len(rec.emitted))
        self._release_slot(slot, cold=True)
        self._preempted.append(rec)
        self.stats["preemptions"] += 1
        if self._tele is not None:
            self._tele.on_preempt(st.rid, len(self._preempted))
            self._pool_gauges()

    # ------------------------------------------------------------ steps
    def _build_decode_step(self):
        """One jitted program running ``tick_block`` decode steps per
        host dispatch (lax.scan; emits the [slots, n] token matrix).
        Larger blocks amortize dispatch (the measured relay cost is
        ~8.6 ms/dispatch vs sub-ms chip work) at the price of admission
        latency and ≤n-1 wasted steps on slots that finish mid-block —
        wasted rows write out of bounds (dropped) or above the frontier
        (masked), never corrupting live slots."""
        embed_p, step_p, head_p = (self._embed_fn, self._step_fn,
                                   self._head_fn)
        do_sample = self.do_sample
        temperature, top_k, top_p = (self._temperature, self._top_k,
                                     self._top_p)
        n = self.tick_block

        def one(tok, caches, t, keys):
            x = embed_p(tok, t)
            out, caches = step_p(x, caches, t)
            logits = head_p(out)
            if logits.ndim == 3:
                logits = logits[:, -1]
            if do_sample:
                from .decode_loop import process_logits

                def samp(k, row):
                    # identical draw chain to sample_generate.body:
                    # split this slot's key, sample over its [1, V] row
                    k2, sub = jax.random.split(k)
                    nxt = jax.random.categorical(
                        sub, process_logits(row[None], temperature,
                                            top_k, top_p), axis=-1)[0]
                    return k2, nxt.astype(jnp.int32)

                keys, nxt = jax.vmap(samp)(keys, logits)
            else:
                nxt = jnp.argmax(logits, -1).astype(jnp.int32)
            return nxt, caches, t + 1, keys

        def block(tok, caches, t, keys):
            def body(carry, _):
                carry = one(*carry)
                return carry, carry[0]
            (tok, caches, t, keys), toks = jax.lax.scan(
                body, (tok, caches, t, keys), None, length=n)
            return tok, caches, t, keys, jnp.transpose(toks, (1, 0))

        return jax.jit(block, donate_argnums=(1,))

    def _build_fused_step(self):
        """One jitted program running a WHOLE serving tick: the model
        bundle's raw fused-tick entry (prefill chunks + s=1 decode
        rows over a live-page DMA schedule) with the sampling epilogue
        folded in — first-token draws for slots completing their
        prompt this launch (``fresh`` slots seed their chain from
        ``seeds`` INSIDE the program, bit-identical to the host-eager
        ``PRNGKey``/split/categorical chain the split path runs) and
        decode-row draws continuing carried ``keys``. Non-emitting
        slots pass their keys through untouched, so the per-request
        chains stay exactly ``sample_generate``'s. One dispatch per
        tick: {"fused": 1}.

        The jitted program is cached process-wide per (bundle entry,
        sampling params): N servers over the same model — a replica
        fleet, or a bench's split/fused pair — share one compile per
        geometry point instead of re-tracing per instance."""
        fused_fn = self._fused_fn
        do_sample = self.do_sample
        temperature, top_k, top_p = (self._temperature, self._top_k,
                                     self._top_p)
        key = (fused_fn, do_sample, temperature, top_k, top_p)
        cached = _FUSED_STEP_CACHE.get(key)
        if cached is not None:
            return cached

        def fused_step(tokens, t0, last, dec, emit, fresh, seeds,
                       out_idx, keys, bt_live, ss, sp, caches):
            logits, caches = fused_fn(tokens, t0, last, dec, caches,
                                      out_idx, bt_live, ss, sp)
            if do_sample:
                from .decode_loop import process_logits
                fresh_keys = jax.vmap(jax.random.PRNGKey)(seeds)
                keys_in = jnp.where((fresh > 0)[:, None], fresh_keys,
                                    keys)

                def samp(k, row):
                    # identical draw chain to sample_generate.body /
                    # _activate: split this slot's key, sample over
                    # its [1, V] row
                    k2, sub = jax.random.split(k)
                    nxt = jax.random.categorical(
                        sub, process_logits(row[None], temperature,
                                            top_k, top_p), axis=-1)[0]
                    return k2, nxt.astype(jnp.int32)

                new_keys, nxt = jax.vmap(samp)(keys_in, logits)
                keys_out = jnp.where((emit > 0)[:, None], new_keys,
                                     keys_in)
            else:
                nxt = jnp.argmax(logits, -1).astype(jnp.int32)
                keys_out = keys
            return nxt, keys_out, caches

        prog = jax.jit(fused_step, donate_argnums=(12,))
        _FUSED_STEP_CACHE[key] = prog
        while len(_FUSED_STEP_CACHE) > _FUSED_STEP_CACHE_MAX:
            _FUSED_STEP_CACHE.pop(next(iter(_FUSED_STEP_CACHE)))
        return prog

    def _activate_fused(self, slot, first):
        """A slot's prompt completed inside the fused launch and its
        first token was drawn there too: flip it into the decode
        phase. The split path's device state pushes (``_pending_*``)
        don't exist here — the next tick's launch carries the token
        and key as arguments."""
        st = self._slots[slot]
        st.phase = "decode"
        self._active[slot] = True
        self._prefill_fifo.remove(slot)
        st.emitted.append(first)
        if st.journey is not None:
            st.journey.event("first_token")
        st.stream(self._deferred_cbs)
        self.stats["admissions"] += 1
        if self._tele is not None:
            self._tele.on_first_token(st.rid, st.prompt_len - st.n_pre,
                                      st.n_pre)

    def _step_fused(self):
        """One fused serving tick (``serving_mode="fused"``): admit
        (reservations only), pack every slot's work — the next prompt
        chunk of each mid-prefill slot under the per-tick token
        budget, the single decode row of each live slot — and run it
        as ONE program over a DMA schedule covering only live pages.
        Mid-prefill slots are REAL prefill rows (no null-redirected
        decode rides), idle slots are kernel-skipped, and the
        admission-tick extras of the split path (separate prefill
        launch, state pushes, block-table sync) ride the launch as
        program arguments — the tick's dispatch profile is
        {"fused": 1}."""
        self._prefill_used = 0
        self._expire_locked()
        self._admit(run_prefill=False)     # reserve; chunks ride the launch
        if self._phase_timer is not None:
            self._phase_timer.mark("admission")
        # harvest BEFORE packing: a slot whose budget is spent (or that
        # emitted eos at activation) must not decode further
        self._harvest()
        if self._optimistic and self._active.any():
            # grow every decode slot about to cross its coverage NOW —
            # the launch must never write a needed row through a
            # missing page (rows past the extent null-redirect as in
            # split mode)
            self._grow_locked()
        S = self.max_slots
        pg = self.page_size
        budget = self._prefill_budget
        plan = []                          # (slot, start, take)
        used = 0
        for slot in self._prefill_fifo:
            if used >= budget:
                break
            st = self._slots[slot]
            take = min(st.prompt_len - st.fill_pos, budget - used)
            plan.append((slot, st.fill_pos, take))
            used += take
        dec_slots = [s for s in range(S) if self._active[s]]
        if not plan and not dec_slots:
            if self._tele is not None:
                self._tele.set_active_slots(0)
            return 0
        self._prefill_used += used
        # pack geometry rides pow2 ladders. The min-2 chunk-width floor
        # keeps the PR-6 multi-row bit-parity guarantee for PREFILL
        # rows only; decode rows take the s=1 fallback path whatever C
        # is, so a decode-only tick (no plan) packs C=1 — the
        # steady-state shape — instead of burning a zero pad row per
        # slot (one extra ladder signature, half the per-token pad).
        if plan:
            max_take = max(t for _, _, t in plan)
            C = max(2, 1 << (max_take - 1).bit_length())
        else:
            C = 1
        tokens = np.zeros((S, C), np.int32)
        t0 = np.full((S,), self.max_cache_len, np.int32)   # idle sentinel
        last = np.full((S,), -1, np.int32)
        dec = np.zeros((S,), np.int32)
        emit = np.zeros((S,), np.int32)
        fresh = np.zeros((S,), np.int32)
        seeds = np.zeros((S,), np.int32)
        out_idx = np.zeros((S,), np.int32)
        done = []
        for slot, start, take in plan:
            st = self._slots[slot]
            tokens[slot, :take] = st.ids[start:start + take]
            t0[slot] = start
            last[slot] = start + take - 1
            if start + take == st.prompt_len:
                out_idx[slot] = take - 1
                emit[slot] = fresh[slot] = 1
                # two's-complement wrap to int32 (np.int32(big) RAISES
                # under NumPy 2): PRNGKey of the wrapped value is
                # bit-identical to the host path's PRNGKey(st.seed)
                s = st.seed & 0xffffffff
                seeds[slot] = s - 0x100000000 if s >= 0x80000000 else s
                done.append(slot)
        for slot in dec_slots:
            st = self._slots[slot]
            t = st.prompt_len + len(st.emitted) - 1
            tokens[slot, 0] = st.emitted[-1]
            t0[slot] = last[slot] = t
            dec[slot] = emit[slot] = 1
        # live block-table slice + DMA schedule: the launch's page
        # traffic covers exactly the live frontier, whatever the
        # configured table width (the skipped-page-DMA cut, lifted)
        from ..ops.pallas.fused_tick import build_schedule
        live_pages = max(int(l) // pg + 1 for l in last if l >= 0)
        W = min(self._bt_pages,
                max(1, 1 << (live_pages - 1).bit_length()))
        bt_live = np.ascontiguousarray(self._kv.block_table[:, :W])
        ss, sp, n_live = build_schedule(last, pg, n_slots=S)
        self._kv.dirty = False     # the slice IS the device's view
        if self._faults is not None:
            # chaos failure point: a dying fused tick is a SERVER-level
            # transient — the supervisor retries it (host state is
            # consistent: slot bookkeeping happens after the dispatch)
            self._faults.check(faults.DECODE_TICK)
        tele = self._tele
        n_active = len(dec_slots)
        t_tick = tele.tick_started() if tele is not None else None
        t_pre = tele.prefill_started() if (tele is not None and plan) \
            else None
        wall0 = _time_mod.perf_counter()
        if self._fused_jit is None:
            self._fused_jit = self._build_fused_step()
        args = (jnp.asarray(tokens), jnp.asarray(t0), jnp.asarray(last),
                jnp.asarray(dec), jnp.asarray(emit), jnp.asarray(fresh),
                jnp.asarray(seeds), jnp.asarray(out_idx),
                jnp.asarray(self._host_keys), jnp.asarray(bt_live),
                jnp.asarray(ss), jnp.asarray(sp), self._caches)
        fn = self._fused_jit
        if self._costs is not None:
            # one priced program per (C, W, G) ladder point, cached
            # host-side like _decode_prog (no per-tick pytree hashing)
            key = (C, W, len(ss))
            prog = self._fused_progs.get(key)
            if prog is None:
                prog = self._cost_program(self._cost_op("fused"),
                                          self._fused_jit, args)
                self._fused_progs[key] = prog
            fn = prog
        nxt, keys_out, self._caches = fn(*args)
        nxt = np.asarray(nxt)              # syncs the dispatch
        self._host_keys = np.asarray(keys_out)
        if plan:
            # the launch carries this tick's admission-path prefill
            # work: it IS the admission dispatch (stats/telemetry keep
            # their per-admission meaning)
            self._count_dispatches(1, op="fused")
        else:
            self._tick_dispatch("fused")
        if self._phase_timer is not None:
            self._phase_timer.mark("fused_launch")
        led = self._led
        for slot, start, take in plan:
            st = self._slots[slot]
            st.fill_pos = st.filled = start + take
            self.stats["prefill_tokens"] += take
            if led is not None:
                if st.preempts:
                    led.add("replay", take)
                else:
                    tail = max(0, min(start + take,
                                      st.reprefill_upto) - start)
                    led.add("tail_reprefill", tail)
                    led.add("goodput", take - tail)
                led.add("chunk_pad", C - take)
            if st.journey is not None:
                st.journey.event("prefill_chunk", start=start,
                                 take=take)
        for slot in done:
            self._activate_fused(slot, int(nxt[slot]))
        decoded = 0
        for slot in dec_slots:
            st = self._slots[slot]
            st.emitted.append(int(nxt[slot]))
            if led is not None:
                # a resumed slot's rows below its pre-preemption
                # offset re-generate tokens the waiter already has
                led.add("replay"
                        if len(st.emitted) <= len(st.replayed)
                        else "goodput", 1)
                led.add("chunk_pad", C - 1)   # the decode row's C-1 pad
            decoded += 1
            st.stream(self._deferred_cbs)
        if led is not None and len(ss) > n_live:
            # the ONLY masked DMA left: the schedule's quarter-octave
            # ladder pad entries (kernel-skipped compute, modeled as
            # page DMAs like the split mode's full-width cut they
            # replace; bounded at ~25% of live entries)
            led.add("skipped_page_dma", (len(ss) - n_live) * pg)
        if plan:
            self.stats["prefill_wall_s"] += \
                _time_mod.perf_counter() - wall0
        if tele is not None:
            tele.on_tick(t_tick, n_active, decoded)
            if t_pre is not None:
                # the launch wall covers decode rows too — documented:
                # fused prefill seconds are launch seconds
                tele.on_prefill_batch(t_pre, used)
        self._harvest()
        # end-of-tick admissions reserve only: their chunks ride the
        # NEXT tick's launch (the token budget is per tick)
        self._admit(run_prefill=False)
        n = int(self._active.sum())
        if tele is not None:
            tele.set_active_slots(n)
        return n

    def step(self):
        """One server tick: admit waiting requests, run ``tick_block``
        batched decode steps as one program, harvest finished rows.
        Returns the number of active slots after the tick."""
        with self._lock:
            n = self._step_locked()
            if self._prefix is not None:
                self._prefix.flush_sketch()   # one publish per tick
        self._fire_callbacks()
        return n

    def _fire_callbacks(self):
        """Run streamed-token callbacks collected during locked work.
        EVERY queued callback fires even when one raises — a poisoned
        stream must not starve the other requests' chunks (they were
        already swapped out of ``_deferred_cbs`` and would be lost) —
        then the failures are re-raised together as a ``CallbackError``
        (``.errors`` per rid, ``__cause__`` the first) to the
        step()/run() caller or the supervised serve loop, which fails
        exactly the offending requests."""
        cbs, self._deferred_cbs = self._deferred_cbs, []
        ct = self._costs
        t_cb = ct.clock.now() if (ct is not None and cbs) else None
        errors = []
        for cb, rid, toks in cbs:
            try:
                if self._faults is not None:
                    self._faults.check(faults.ON_TOKEN, rid=rid)
                cb(rid, toks)
            except Exception as e:
                errors.append((rid, e))
        if t_cb is not None:
            # fires OUTSIDE the lock after the tick flushed, so this
            # phase folds into the NEXT tick's breakdown (a one-tick
            # skew, documented in telemetry.costs)
            ct.add_phase("token_callbacks", ct.clock.now() - t_cb)
        if errors:
            raise CallbackError(errors, what="on_token callback")

    def _step_locked(self):
        """One tick under the lock. Wraps the real work so the tick's
        host->device dispatch profile is published however the tick
        exits (normal, drained early-return, or a raising fault — a
        partial profile in the recorder is exactly what a postmortem
        wants to see)."""
        self._tick_disp = {}
        ct = self._costs
        if ct is not None:
            self._phase_timer = ct.phase_timer()
        try:
            return self._step_inner()
        finally:
            if self._phase_timer is not None:
                # trailing work since the last mark (token-emit loop,
                # end-of-tick harvest/admit, or an early return's
                # remainder) is bookkeeping
                self._phase_timer.close("bookkeeping")
            prof = self._tick_disp
            if prof:
                total = sum(prof.values())
                self.stats["tick_dispatches"] += total
                if self._tele is not None:
                    self._tele.on_tick_dispatches(prof)
                if self._rec is not None:
                    extra = {}
                    if ct is not None:
                        extra["phases"] = ct.pending_phases()
                    self._rec.record("tick", dispatches=dict(prof),
                                     total=total,
                                     active=int(self._active.sum()),
                                     **extra)
            if self._led is not None:
                # the conservation boundary: whatever this tick
                # attributed (even a partial, faulted tick) is folded
                # and published NOW — kinds sum to the tick's device
                # tokens by construction of the sites above
                self._led.flush_tick()
            if ct is not None:
                # same boundary for the cost side: fold charges +
                # phases, publish FLOPs/bytes/MFU, advance the compile
                # watch's warmup
                ct.flush_tick()
                self._phase_timer = None

    def _step_inner(self):
        if self._fused:
            # serving_mode="fused": the whole tick is one program
            return self._step_fused()
        self._prefill_used = 0       # per-tick prefill token budget
        self._expire_locked()
        self._admit()
        if self._phase_timer is not None:
            # scheduling work minus the prefill launches (those mark
            # themselves out as "prefill_launch" from inside)
            self._phase_timer.mark("admission")
        if not self._active.any():
            if self._tele is not None:     # keep the gauge live when a
                self._tele.set_active_slots(0)   # drained tick skips decode
            return 0
        # harvest BEFORE stepping: a slot whose budget is spent (or that
        # emitted eos at admission) must not decode further
        self._harvest()
        if self._phase_timer is not None:
            self._phase_timer.mark("bookkeeping")
        if not self._active.any():
            if self._tele is not None:
                self._tele.set_active_slots(0)
            return 0
        if self._kv is not None:
            # reserve mode: admission took each slot's FULL extent
            # (prompt + budget), so no page growth happens mid-flight.
            # optimistic mode: grow every slot about to cross its
            # coverage NOW, preempting victims if the pool is dry —
            # the dispatch below must never write a needed row through
            # a missing page. Writes past a slot's table (wasted block
            # steps of finished/inactive rows) are redirected to the
            # null page and need no coverage in either mode.
            if self._optimistic:
                self._grow_locked()
                if not self._active.any():
                    # extreme pressure: growth parked every decoding
                    # slot — nothing to dispatch this tick (re-admission
                    # restarts them next tick)
                    if self._tele is not None:
                        self._tele.set_active_slots(0)
                    return 0
            self._sync_block_table()
        # ragged mode: activations batched their tok/t/key updates —
        # push them (and the parked write positions of slots still
        # prefilling: their wasted decode writes must null-redirect,
        # not land in the pages being filled) before the decode program
        self._flush_slot_state()
        if self._decode_jit is None:
            self._decode_jit = self._build_decode_step()
        if self._faults is not None:
            # chaos failure point: a dying decode tick is a SERVER-level
            # transient — the supervisor retries it (host state is
            # consistent: nothing was dispatched yet)
            self._faults.check(faults.DECODE_TICK)
        tele = self._tele
        n_active = int(self._active.sum())
        t_tick = tele.tick_started() if tele is not None else None
        decode_fn = self._decode_jit
        if self._costs is not None:
            # the catalog's AOT executable is the SAME HLO the jit
            # cache would build (bit-identical tokens); calling it
            # charges the compiled program's FLOPs/bytes per dispatch.
            # Priced ONCE and cached: the decode signature is static
            # by construction (fixed slot count / cache geometry), so
            # the hot loop must not re-hash the caches pytree per tick
            if self._decode_prog is None:
                self._decode_prog = self._cost_program(
                    self._cost_op("decode"), self._decode_jit,
                    (self._tok, self._caches, self._t, self._keys))
            decode_fn = self._decode_prog
        (self._tok, self._caches, self._t, self._keys,
         toks) = decode_fn(self._tok, self._caches, self._t,
                           self._keys)
        self._tick_dispatch("decode")
        toks = np.asarray(toks)                    # [slots, tick_block]
        if self._phase_timer is not None:
            # covers grow/state-flush/block-table sync, the decode
            # compile (watched separately), dispatch, and device sync
            self._phase_timer.mark("decode_launch")
        decoded = wasted = 0
        led = self._led
        if led is not None:
            # rows of slots holding no live decode work still ride the
            # program: empty slots and mid-prefill slots (parked past
            # the table so their writes null-redirect; the dense
            # backend drops them out of bounds — same waste class)
            led.add("null_redirect",
                    (self.max_slots - n_active) * toks.shape[1])
        for slot in range(self.max_slots):
            if not self._active[slot]:
                continue
            st = self._slots[slot]
            if led is not None and self._kv is not None:
                led.add("skipped_page_dma", self._skipped_dma(
                    st.prompt_len + len(st.emitted)))
            for j in range(toks.shape[1]):
                st.emitted.append(int(toks[slot, j]))
                if led is not None:
                    # a resumed slot's rows below its pre-preemption
                    # offset re-generate tokens the waiter already has
                    led.add("replay"
                            if len(st.emitted) <= len(st.replayed)
                            else "goodput", 1)
                if self._finished(st):
                    wasted += toks.shape[1] - (j + 1)
                    if led is not None:
                        led.add("block_waste", toks.shape[1] - (j + 1))
                    break              # later block tokens are waste
            decoded += min(j + 1, toks.shape[1])
            st.stream(self._deferred_cbs)
        if tele is not None:
            # np.asarray above synced the dispatch, so the tick time
            # covers host dispatch + device work
            tele.on_tick(t_tick, n_active, decoded)
            if wasted:
                tele.add_wasted_block_tokens(wasted)
            if self._kv is not None:
                # inactive rows still step; their writes go through an
                # all-null block table row straight to the null page
                tele.add_null_writes(
                    (self.max_slots - n_active) * toks.shape[1])
        self._harvest()
        # end-of-tick admissions reserve only (ragged: their prefill
        # chunks run at the NEXT tick's single batched launch — the
        # token budget is per tick); the dense path prefills inline
        self._admit(run_prefill=False)
        n = int(self._active.sum())
        if tele is not None:
            tele.set_active_slots(n)
        return n

    def _busy_locked(self):
        """Work pending: queued requests, decoding slots, slots still
        mid-ragged-prefill (not yet _active but holding pages and owed
        their remaining prompt chunks), or preempted requests parked
        for re-admission (``stop(drain=True)`` keeps ticking until
        they finish too)."""
        return bool(self._queue or self._active.any()
                    or self._prefill_fifo or self._preempted)

    def _finished(self, st):
        if len(st.emitted) >= st.budget:
            return True
        return (self.eos_token_id is not None
                and st.emitted[-1] == self.eos_token_id)

    def _harvest(self):
        finished = False
        for slot in range(self.max_slots):
            st = self._slots[slot]
            if self._active[slot] and self._finished(st):
                out = np.asarray(st.emitted[:st.budget], np.int32)
                self._results[st.rid] = out
                self._release_slot(slot)   # paged: donates prompt pages
                if self._tele is not None:
                    self._tele.on_finish(st.rid, len(out))
                if self._rec is not None:
                    self._rec.record("finish", rid=st.rid,
                                     tokens=len(out))
                if st.journey is not None:
                    st.journey.event("finished", tokens=len(out))
                finished = True
        if finished:
            if self._tele is not None:
                self._pool_gauges()
            self._done_cv.notify_all()

    # ------------------------------------------------------- reliability
    def _expire_locked(self):
        """Fail queued requests whose deadline passed (BEFORE a prefill
        is wasted on them) and cancel expired mid-decode slots (their
        partial tokens become the recorded result). Reads the clock at
        most once, and only when some live request carries a deadline."""
        now = None
        notify = False
        if any(item.deadline is not None for item in self._queue):
            now = self._clock.now()
            keep = []
            for item in self._queue:
                if item.deadline is not None and now >= item.deadline:
                    err = DeadlineExceeded(
                        f"request {item.rid} expired in queue "
                        f"(deadline passed before admission)")
                    self._failures[item.rid] = err
                    notify = True
                    if self._tele is not None:
                        self._tele.on_deadline_expired("queued")
                        self._tele.on_admission_failure(item.rid, err)
                    if self._rec is not None:
                        self._rec.record("deadline", rid=item.rid,
                                         where="queued")
                    if item.journey is not None:
                        # NB "where" is a Journey reserved key (the
                        # hop label) — the expiry location is "at"
                        item.journey.event("expired", at="queued")
                else:
                    keep.append(item)
            if len(keep) != len(self._queue):
                self._queue[:] = keep
                if self._tele is not None:
                    self._tele.set_queue_depth(len(self._queue))
        for slot in range(self.max_slots):
            st = self._slots[slot]
            if st is None or st.deadline is None:
                continue
            if st.phase == "migrating":
                # its pages are in flight to a sibling: expiring the
                # slot here would tear down state migrate_finish/
                # migrate_abort still owns. The pause spans ONE
                # migration attempt; the deadline bites again the
                # moment the slot resumes (or on the target)
                continue
            if now is None:
                now = self._clock.now()
            if now >= st.deadline:
                # decoding (partial tokens kept) or mid-ragged-prefill
                # (empty partial) — either way the slot frees now
                if self._rec is not None:
                    self._rec.record("deadline", rid=st.rid,
                                     where="decoding")
                if st.journey is not None:
                    st.journey.event("expired", at="decoding")
                self._finish_partial_locked(slot)
                notify = True
                if self._tele is not None:
                    self._tele.on_deadline_expired("decoding")
                    self._tele.on_cancel(st.rid)
                    self._pool_gauges()
        if self._preempted:
            keep_p = []
            for rec in self._preempted:
                if rec.deadline is not None:
                    if now is None:
                        now = self._clock.now()
                    if now >= rec.deadline:
                        if self._rec is not None:
                            self._rec.record("deadline", rid=rec.rid,
                                             where="preempted")
                        if rec.journey is not None:
                            rec.journey.event("expired",
                                              at="preempted")
                        # deadline accounting holds ACROSS preemption:
                        # time parked counted against the same absolute
                        # deadline. Same promise as mid-decode expiry —
                        # the pre-preemption partial is the result, no
                        # decode is resumed, and its pages were already
                        # donated/freed at preemption
                        self._flush_parked_locked(rec)
                        notify = True
                        if self._tele is not None:
                            self._tele.on_deadline_expired("preempted")
                            self._tele.on_cancel(rec.rid)
                        continue
                keep_p.append(rec)
            if len(keep_p) != len(self._preempted):
                self._preempted[:] = keep_p
                self._preempt_gauge()
        if notify:
            self._done_cv.notify_all()

    def _fail_request_locked(self, rid, err):
        """Fail ONE request still LIVE (queued or in-flight) with
        ``err`` — the per-request channel the supervisor uses so a
        poisoned callback or injected per-request fault never takes the
        server down. A rid that is in neither place already settled
        (harvested — result recorded or even collected — or failed):
        e.g. the FINAL stream chunk's callback raised after harvest.
        Recording a failure then would leave a phantom ``failures``
        entry no wait() ever pops, so it is skipped."""
        found, journey = False, None
        for i, item in enumerate(self._queue):
            if item.rid == rid:
                del self._queue[i]
                found, journey = True, item.journey
                break
        if not found:
            for slot in range(self.max_slots):
                st = self._slots[slot]
                if st is not None and st.rid == rid:
                    self._release_slot(slot)
                    if self._tele is not None:
                        self._pool_gauges()
                    found, journey = True, st.journey
                    break
        if not found:
            for i, rec in enumerate(self._preempted):
                if rec.rid == rid:
                    del self._preempted[i]
                    self._preempt_gauge()
                    found, journey = True, rec.journey
                    break
        if not found:
            return
        # a failed request has no result: its undelivered stream chunks
        # must not fire later as if it were still live
        self._deferred_cbs = [c for c in self._deferred_cbs
                              if c[1] != rid]
        self._failures[rid] = err
        if self._tele is not None:
            self._tele.on_admission_failure(rid, err)
        self._note_request_failure_locked(rid, err, journey)
        self._done_cv.notify_all()

    def _note_request_failure_locked(self, rid, err, journey=None,
                                     bundle=True):
        """Observability funnel for one request FAILING (as opposed to
        finishing with a partial): journey phase, recorder event, and a
        postmortem bundle — "a request just died" is exactly the moment
        an operator wants the last N events and the pool state frozen.
        ``bundle=False`` skips the capture for EXPECTED sheds (the
        evict_oldest path runs on every overloaded submit(): paying a
        state snapshot there would tax the hot path and flood the
        bounded bundle store out of its genuinely interesting
        captures). The caller owns the actual failure bookkeeping."""
        if journey is not None:
            journey.event("failed", error=type(err).__name__)
        if self._rec is not None:
            self._rec.record("fail", rid=rid,
                             error=type(err).__name__)
            if bundle:
                self._postmortem_locked("request_failed", rid=rid,
                                        error=repr(err))

    def _postmortem_locked(self, reason, **extra):
        """Capture a postmortem bundle into the flight recorder: recent
        events plus the serving state an incident review needs — pool
        balance, block-table occupancy, radix-tree stats, the parked
        queue, live slots, queue depth, health, stats. Called under the
        server lock; returns the bundle (or None without a recorder)."""
        if self._rec is None:
            return None
        sections = {
            "health": self._health.state,
            "stats": dict(self.stats),
            "queue": [item.rid for item in self._queue],
            "slots": [{"slot": s, "rid": st.rid, "phase": st.phase,
                       "emitted": len(st.emitted),
                       "priority": st.priority}
                      for s, st in enumerate(self._slots)
                      if st is not None],
            "parked": [{"rid": rec.rid, "priority": rec.priority,
                        "preempts": rec.preempts,
                        "emitted": len(rec.emitted)}
                       for rec in self._preempted],
            # live KV-page migration state: the in-flight pauses an
            # incident interrupted plus the cumulative outcome split —
            # "did this replica hand its work off or flush it?" is the
            # first question a drain/crash review asks
            "migration": {
                "in_flight": sorted(self._migrating),
                "staging": sorted(self._staging),
                "migrations": self.stats["migrations"],
                "fallbacks": self.stats["migration_fallbacks"],
                "migrated_in": self.stats["migrated_in"],
                "handoff_pages_out": self.stats["handoff_pages_out"],
                "handoff_pages_in": self.stats["handoff_pages_in"]},
        }
        if self._kv is not None:
            # pool_balance() is the ONE definition of the balance the
            # chaos suites assert on (re-entrant lock: safe here) —
            # the bundle must never drift from it
            bal = self.pool_balance()
            sections["pool_balance"] = {
                "free": bal[0], "live": bal[1], "pinned": bal[2],
                "cached": bal[3], "preempted": bal.preempted,
                "preemptions": bal.preemptions,
                "num_shards": bal.num_shards,
                "per_shard": list(bal.per_shard),
                "shard_page_bytes": bal.shard_page_bytes,
                "host": bal.host, "host_bytes": bal.host_bytes}
            sections["block_table"] = self._kv.occupancy(
                num_shards=self._pool_shards, host_tier=self._host)
            sections["prefix_cache"] = self._prefix.stats()
        if self._led is not None:
            # how much of the hardware's recent work was useful is
            # exactly what an incident review wants next to the pool
            # state ("were we thrashing before this died?")
            sections["goodput"] = self._led.snapshot()
        if self._costs is not None:
            # per-op FLOPs/bytes totals, compile counts, and the last
            # tick's phase breakdown — "was it host-bound" answerable
            # from the crash scene without a live server
            sections["costs"] = self._costs.snapshot()
        sections.update(extra)
        return self._rec.postmortem(reason, **sections)

    def postmortems(self):
        """Captured postmortem bundles, oldest first (empty without a
        recorder) — served over ``/debug/postmortem`` via
        ``serving.serve_metrics``."""
        return [] if self._rec is None else self._rec.postmortems()

    def journey(self, rid):
        """Timeline of a SELF-MINTED journey (standalone server
        constructed with ``journeys=``): the request's phase events in
        arrival order, or None without a journey recorder / for an
        unknown-evicted rid / for a request whose journey was minted
        by a router (query the router for those — its id space, its
        timeline). Served over ``/debug/journey/<rid>`` via
        ``serving.serve_metrics``."""
        if self._jrec is None:
            return None
        return self._jrec.journey(f"s{int(rid)}")

    def goodput(self):
        """The goodput ledger's cumulative snapshot (``{"tokens":
        {kind: n}, "goodput_ratio": ...}``), or None without an
        enabled ledger — also ``/stats["goodput"]`` via
        ``serving.serve_metrics`` and the ``goodput`` postmortem
        section."""
        return None if self._led is None else self._led.snapshot()

    def device_costs(self):
        """The cost catalog's cumulative snapshot (per-op FLOPs/HBM
        bytes, compile counts, recompiles/warmup state, MFU/roofline,
        last tick's phase breakdown), or None without an enabled
        catalog — also ``/stats["costs"]`` via
        ``serving.serve_metrics`` and the ``costs`` postmortem
        section."""
        return None if self._costs is None else self._costs.snapshot()

    def utilization(self):
        """Per-replica utilization digest for routing-side views: the
        goodput ratio (ledger) and MFU (cost catalog) — whatever is
        wired. Rides remote heartbeat digests (``inference.remote``)
        so ``/fleet`` and the router see per-replica utilization
        without a registry pull; cheap enough for a heartbeat cadence
        (one short ledger lock, one attribute read)."""
        util = {}
        if self._led is not None:
            util["goodput_ratio"] = self._led.goodput_ratio()
        if self._costs is not None:
            util["mfu"] = self._costs.mfu()
        return util

    def _fail_all_locked(self, cause):
        """Breaker-open path: fail EVERY queued and in-flight request
        with a ``CircuitOpenError`` so no waiter wedges on a server
        that cannot currently tick."""
        thresh = self._sup.breaker.failure_threshold
        for item in self._queue:
            if item.journey is not None:
                item.journey.event("failed", error="CircuitOpenError")
        for rec in self._preempted:
            if rec.journey is not None:
                rec.journey.event("failed", error="CircuitOpenError")
        for st in self._slots:
            if st is not None and st.journey is not None:
                st.journey.event("failed", error="CircuitOpenError")
        rids = [item.rid for item in self._queue]
        self._queue.clear()
        rids += [rec.rid for rec in self._preempted]
        self._preempted.clear()
        self._preempt_gauge()
        for slot in range(self.max_slots):
            if self._slots[slot] is not None:
                rids.append(self._slots[slot].rid)
                self._release_slot(slot)
        # chunks queued by the failed tick belong to rids that now have
        # no result — firing them after recovery would stream tokens
        # for requests whose wait() already raised
        self._deferred_cbs.clear()
        for rid in rids:
            err = CircuitOpenError(
                f"request {rid} aborted: circuit breaker opened after "
                f"{thresh} consecutive tick failures")
            err.__cause__ = cause
            self._failures[rid] = err
            if self._tele is not None:
                self._tele.on_admission_failure(rid, err)
        if self._tele is not None:
            self._tele.set_queue_depth(0)
            self._tele.set_active_slots(0)
            self._pool_gauges()
        self._done_cv.notify_all()

    @property
    def health(self):
        """Current health state: ``healthy`` / ``degraded`` /
        ``draining`` / ``dead`` (see reliability.health). Lock-free
        read of a plain-string attribute — /healthz must answer while
        a tick (or its first jit compile) holds the serve lock, or the
        readiness probe times out exactly when the server warms up."""
        return self._health.state

    def _publish_health(self, state, code):
        if self._tele is not None:
            self._tele.set_health(state)
        if self._rec is not None:
            self._rec.record("health", state=state)

    def run(self, max_ticks=100000):
        """Drive until queue and slots drain; returns {rid: new_tokens}.
        Requests whose admission failed are left out — their exceptions
        are drained into ``failures`` (per run, so records never
        accumulate across runs)."""
        ticks = 0
        while ticks < max_ticks:
            with self._lock:
                if not self._busy_locked():
                    break
                self._step_locked()
                if self._prefix is not None:
                    self._prefix.flush_sketch()
            self._fire_callbacks()
            ticks += 1
        with self._lock:
            out, self._results = self._results, {}
            self._run_failures, self._failures = self._failures, {}
        return out

    # ------------------------------------------------------ serve thread
    def start(self, idle_sleep=0.005):
        """Run the decode loop on a SUPERVISED background thread:
        submit()/cancel() from any thread; collect results with
        ``wait(rid)``.

        Supervision (reliability.ServeSupervisor): a failing tick is
        retried with exponential backoff (``retry_policy``); a failing
        REQUEST (poisoned on_token callback, injected per-request fault)
        is failed individually through the per-rid failures channel
        while every other slot keeps decoding; after
        ``breaker.failure_threshold`` consecutive tick failures the
        circuit breaker opens — in-flight waiters are unblocked with
        ``CircuitOpenError``, health flips to ``degraded``, and after
        the cooldown a half-open probe tick restores ``healthy``. The
        thread itself survives everything short of interpreter
        shutdown."""
        if self._thread is not None:
            raise RuntimeError("server already started")
        self._stop.clear()
        self._thread_error = None
        with self._lock:
            self._accepting = True
            self._draining = False
            if self._health.state != HEALTHY:
                self._health.reset()   # explicit restart after stop()

        def loop():
            import time as _time
            sup = self._sup
            try:
                while True:
                    with self._lock:
                        busy = self._busy_locked()
                    if self._stop.is_set():
                        if not (self._draining and busy):
                            break
                    if not busy:
                        if (sup.breaker.state != sup.breaker.CLOSED
                                and sup.allow()):
                            # cooldown elapsed with nothing failing:
                            # close the breaker so an IDLE server does
                            # not stay degraded (and alerting) forever
                            sup.success()
                            self._recover_health()
                        _time.sleep(idle_sleep)
                        continue
                    if not sup.allow():          # breaker cooldown
                        with self._lock:
                            # deadlines keep their promise even while
                            # the breaker gates ticks: expire queued/
                            # decoding requests during the cooldown
                            self._expire_locked()
                        _time.sleep(idle_sleep)
                        continue
                    try:
                        with self._lock:
                            if self._busy_locked():
                                self._step_locked()
                            if self._prefix is not None:
                                self._prefix.flush_sketch()
                        self._fire_callbacks()
                    except CallbackError as ce:
                        # the ENGINE is fine — fail exactly the
                        # requests whose streams are poisoned (typed,
                        # so wait(rid) raises it directly)
                        with self._lock:
                            for rid, err in ce.errors:
                                self._fail_request_locked(
                                    rid, CallbackError(
                                        [(rid, err)],
                                        what="on_token callback"))
                        sup.success()
                        self._recover_health()
                    except Exception as e:
                        self._on_tick_failure(e)
                    else:
                        sup.success()
                        self._recover_health()
            except BaseException as e:   # surface to waiters, don't wedge
                with self._lock:
                    self._thread_error = e
                    self._health.to(DEAD)
                    self._done_cv.notify_all()

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def _on_tick_failure(self, e):
        """Supervised-tick failure path (called WITHOUT the lock — the
        retry backoff sleeps here)."""
        if self._tele is not None:
            self._tele.on_tick_retry()
        if self._rec is not None:
            self._rec.record("tick_retry", error=type(e).__name__)
        if self._sup.failure(e) == "open":
            with self._lock:
                self._health.to(DEGRADED)
                if self._rec is not None:
                    self._rec.record("breaker", state="open",
                                     error=type(e).__name__)
                    # capture BEFORE the teardown: the bundle freezes
                    # the parked queue / pool balance / slots as they
                    # were at the moment retries ran out
                    self._postmortem_locked("breaker_open",
                                            error=repr(e))
                self._fail_all_locked(e)
            if self._tele is not None:
                self._tele.on_breaker_open()

    def _recover_health(self):
        with self._lock:
            if self._health.state == DEGRADED:
                self._health.to(HEALTHY)

    def stop(self, timeout=60.0, drain=False):
        """Stop the serve thread. ``drain=True`` is the graceful path:
        admission closes immediately (submits raise ``ServerClosed``),
        health goes ``draining``, the loop keeps ticking until every
        queued and in-flight request has finished (results/failures
        flushed to their waiters), then the thread exits. ``drain=False``
        stops after the current tick; still-pending requests are failed
        with ``ServerClosed`` so no waiter wedges. Either way the server
        ends ``dead`` (503 on /healthz) until ``start()`` is called
        again."""
        with self._lock:
            self._accepting = False
            if drain and self._thread is not None:
                self._draining = True
                self._health.to(DRAINING)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                raise RuntimeError(
                    f"serve thread did not stop within {timeout}s (a "
                    f"tick/compile may still be running); call stop() "
                    f"again to re-join")
            self._thread = None
        with self._lock:
            self._draining = False
            if not drain:
                # hard stop: flush partials for in-flight slots (mid-
                # prefill ones record an empty partial) AND for parked
                # preempted requests (their pre-preemption partial is
                # the result), fail what never ran — every waiter
                # unblocks
                for slot in range(self.max_slots):
                    if self._slots[slot] is not None:
                        self._finish_partial_locked(slot)
                for rec in self._preempted:
                    self._flush_parked_locked(rec)
                self._preempted.clear()
                self._preempt_gauge()
                for item in self._queue:
                    self._failures[item.rid] = ServerClosed(
                        f"request {item.rid} was still queued when the "
                        f"server stopped")
                self._queue.clear()
                self._deferred_cbs.clear()   # nobody will fire them
            self._health.to(DEAD)
            self._done_cv.notify_all()

    # ---------------------- multi-replica front door (inference/router.py)
    def queue_depth(self):
        """Requests waiting for a slot — the router's least-loaded
        signal (with ``in_flight`` and ``pool_balance``). LOCK-FREE
        read of a point-in-time length: a serve thread holds the lock
        for whole ticks, and a router picking a destination must not
        queue behind one — a slightly stale load reading only costs
        placement quality, never correctness."""
        return len(self._queue)

    def in_flight(self):
        """Slots holding a live request (decoding or mid-ragged-
        prefill). Lock-free, same contract as ``queue_depth``."""
        return sum(1 for st in self._slots if st is not None)

    def preempt_pressure(self):
        """Requests parked on the preempted queue — displaced in-flight
        work this replica must REPLAY before it makes progress on new
        traffic. The router folds it into its load score (weighted
        above plain queue depth: a thrashing pool costs every resident
        request, not just the parked ones) so the fleet sheds load away
        from replicas losing the optimistic-admission gamble. Always 0
        under ``admission="reserve"``. Lock-free, same contract as
        ``queue_depth``."""
        return len(self._preempted)

    def abandon(self, rid, err):
        """Record a typed failure for ``rid`` on behalf of a caller
        that HOLDS the request outside this server (the multi-replica
        router: a foreign rid harvested off this replica's queue that
        no route ever claimed) — its waiter's ``wait(rid)`` raises
        ``err`` promptly instead of running out its timeout. No-op
        (returns False) when the rid already settled here."""
        with self._lock:
            if rid in self._results or rid in self._failures:
                return False
            self._failures[rid] = err
            self._done_cv.notify_all()
        return True

    def prefix_sketch(self):
        """Fingerprint set of this replica's radix-tree contents
        (``PrefixCache.sketch()``) — the router's prefix-affinity
        signal. Host-side only, no device reads, and LOCK-FREE: the
        cache maintains the sketch incrementally and publishes an
        immutable snapshot. Empty for the dense backend (no page cache
        to be affine to)."""
        prefix = self._prefix
        return frozenset() if prefix is None else prefix.sketch()

    def evacuate(self, flush_partials=False):
        """Harvest every QUEUED request off this replica and hand it to
        the caller (a router requeues them on sibling replicas). The
        harvested entries carry everything a resubmit needs — prompt,
        budget, the resolved sampling seed (so a sibling draws the
        identical chain), callback, and the ABSOLUTE deadline (time
        already spent queued here keeps counting against it). Nothing
        is recorded in ``failures`` for harvested rids: the caller owns
        them now.

        ``flush_partials=True`` (a DEAD replica being evacuated)
        additionally flushes every in-flight slot's partial tokens to
        its waiter exactly as ``stop(drain=False)`` does — mid-decode
        work is not replayable (the sibling would re-decode from
        scratch and double-stream), so the partial is the result. With
        the default False (e.g. a DRAINING replica) in-flight slots
        keep decoding to completion."""
        with self._lock:
            harvested = list(self._queue)
            self._queue.clear()
            if self._rec is not None:
                self._rec.record("evacuate", harvested=len(harvested),
                                 flush_partials=bool(flush_partials))
            if self._tele is not None:
                # the harvested rids leave THIS replica for good: close
                # their lifecycle spans here (the router re-counts them
                # on whatever sibling they land on)
                for item in harvested:
                    self._tele.on_cancel(item.rid)
            if flush_partials:
                for slot in range(self.max_slots):
                    if self._slots[slot] is not None:
                        st = self._finish_partial_locked(slot)
                        if self._tele is not None:
                            self._tele.on_cancel(st.rid)
                # a dead replica's parked preempted requests are
                # mid-decode work too: not replayable elsewhere without
                # double-streaming, so their partials flush to waiters
                for rec in self._preempted:
                    self._flush_parked_locked(rec)
                    if self._tele is not None:
                        self._tele.on_cancel(rec.rid)
                self._preempted.clear()
                self._preempt_gauge()
                # nobody will fire chunks on a dead replica, and every
                # live rid was just flushed
                self._deferred_cbs.clear()
                if self._tele is not None:
                    # every slot was just torn down — a dead replica
                    # must not report phantom load
                    self._tele.set_active_slots(0)
            if self._prefix is not None:
                self._prefix.flush_sketch()   # flushed slots donated
            if self._tele is not None:
                self._tele.set_queue_depth(0)
                self._pool_gauges()
            self._done_cv.notify_all()
        return harvested

    # ------------------------------------------- live KV-page migration
    def migrate_out(self, rid, partial=False, from_page=0):
        """Gather a live request's FULL resumable state so a sibling
        replica can continue it without re-prefilling: the written pool
        pages (per-shard gathers on a mesh — the ``_spill_payload``
        path the host tier proved), the resolved sampling seed, the
        emitted-token log, and the stream offset. Returns
        ``(state, payloads)`` — ``state`` is a JSON-able dict (page
        payloads carry their sha256 so the target verifies END TO END,
        not just per wire frame), ``payloads`` is one ``[k, v]``
        host-array pair per page.

        Mid-DECODE slots migrate as before. A slot still mid-PREFILL
        migrates too (ISSUE 20): a migration of a slot whose
        ``emitted`` is empty is exactly a disaggregated prefill
        handoff — the state ships ``phase="prefill"`` and
        ``filled`` (rows actually written), the target scatters the
        finished prompt pages and prefills ONLY the remainder from
        ``fill_pos``, and its own activation samples the first token
        from the resolved seed — bit-exact, zero re-prefilled rows.

        ``partial=True`` is the non-pausing PIPELINED half: ship the
        complete, not-yet-shipped prompt pages of a mid-prefill slot
        as one bounded batch and keep prefilling. Returns a fragment
        dict (``base`` page index, ``fill_pos`` progress, ``phase``)
        plus the batch; a slot already past activation returns its
        phase with no payloads, which tells a handoff pump to settle
        with a full ``migrate_out``. Partial ships never pause and
        never leak — ``migrate_abort`` resets the shipped-page cursor
        so a later full handoff re-ships everything.

        ``from_page`` skips pages the target already holds (the pump's
        closing call after partial batches landed).

        The full path PAUSES the slot, not tears it down: stepping
        (decode) or chunking (prefill) stops and its pages stay pinned
        until the caller settles the handoff with ``migrate_finish``
        (target committed — release here, donate the prompt prefix as
        usual) or ``migrate_abort`` (anything failed — resume here
        bit-exactly). Raises ``MigrationError`` when the request is
        not migratable (unknown rid, dense backend, already in
        flight); an injected ``migrate.gather`` fault fires BEFORE the
        pause, so a faulted attempt leaves the slot untouched — never
        a leak."""
        from .kv_tier import _sha256
        with self._lock:
            if self._kv is None:
                raise MigrationError(
                    "cache_backend='dense' has no page pool to migrate "
                    f"(request {rid})")
            slot = next((s for s in range(self.max_slots)
                         if self._slots[s] is not None
                         and self._slots[s].rid == rid), None)
            if slot is None:
                raise MigrationError(
                    f"request {rid} holds no slot here (queued, parked, "
                    f"finished, or foreign rids are not migratable — "
                    f"evacuate/replay covers them)")
            st = self._slots[slot]
            if st.phase not in ("decode", "prefill"):
                raise MigrationError(
                    f"request {rid} is mid-{st.phase} — only decoding "
                    f"or prefilling slots migrate")
            if st.phase == "decode" and not st.emitted:
                # unobservable in practice (activation samples the
                # first token atomically with the final prefill chunk)
                # but keep the invariant typed
                raise MigrationError(
                    f"request {rid} has no resumable decode state yet")
            if rid in self._migrating:
                raise MigrationError(
                    f"request {rid} already has a migration in flight")
            if partial:
                # non-pausing: no gather fault either — a pump polls
                # this dozens of times per handoff and chaos belongs
                # on the wire (net.page_send), not on every poll
                return self._migrate_partial_locked(slot, st)
            if self._faults is not None:
                self._faults.check(faults.MIGRATE_GATHER, rid=rid)
            t0 = self._tele.migration_started() \
                if self._tele is not None else None
            if st.phase == "decode":
                # the LAST emitted token is the decode program's
                # pending input — sampled but not yet written, so the
                # target rewrites nothing and re-prefills nothing
                written = st.prompt_len + len(st.emitted) - 1
            else:
                # empty-`emitted` prefill handoff: everything below
                # `filled` is final (chunk boundaries don't change the
                # rows); the target resumes chunking at fill_pos
                written = st.filled
            npages = self._npages_for(written)
            base = max(0, min(int(from_page), npages))
            pages = self._kv.slot_pages(slot)[base:npages]
            payloads = [self._spill_payload(p) for p in pages]
            if self._costs is not None:
                self._charge_transfer(
                    "page_migrate",
                    2 * len(payloads) * self._kv.page_size
                    * self._row_nbytes())
            remaining = None if st.deadline is None else \
                max(0.0, st.deadline - self._clock.now())
            state = {
                "rid": rid,
                "ids": [int(t) for t in st.ids],
                "prompt_len": int(st.prompt_len),
                "budget": int(st.budget),
                "seed": int(st.seed),
                "emitted": [int(t) for t in st.emitted],
                "replayed": [int(t) for t in st.replayed],
                "streamed": int(st.streamed),
                "preempts": int(st.preempts),
                "priority": int(st.priority),
                "n_pre": int(st.n_pre),
                "deadline_s": remaining,
                "page_size": int(self._kv.page_size),
                "written": int(written),
                "phase": st.phase,
                "fill_pos": int(st.fill_pos),
                "filled": int(st.filled),
                "base": int(base),
                "sha256": [_sha256(p) for p in payloads],
            }
            # pause: the decode tick skips inactive rows, the ragged
            # prefill planner skips slots out of the fifo, and (split
            # mode) the device write cursor parks on the null page —
            # resume re-pushes tok/t/key exactly as _activate does (or
            # re-queues the fifo for a prefill slot), so nothing the
            # device scribbles while paused is ever read
            prior = st.phase
            self._active[slot] = False
            st.phase = "migrating"
            if prior == "prefill" and slot in self._prefill_fifo:
                self._prefill_fifo.remove(slot)
            if not self._fused:
                self._pending_t[slot] = self.max_cache_len
            self._migrating[rid] = (slot, t0, prior)
            if self._rec is not None:
                self._rec.record("migrate_out", rid=rid,
                                 pages=npages - base, phase=prior,
                                 tokens=len(st.emitted))
            if st.journey is not None:
                if prior == "prefill":
                    st.journey.event("handoff", at="source",
                                     pages=npages - base,
                                     filled=int(st.filled))
                else:
                    st.journey.event("migrating", at="source",
                                     pages=npages,
                                     tokens=len(st.emitted))
            return state, payloads

    def _migrate_partial_locked(self, slot, st):
        """One bounded, NON-pausing batch of a mid-prefill slot's
        complete, not-yet-shipped pages (``migrate_out(partial=True)``
        body). The fragment's ``base``/``fill_pos``/``phase`` tell the
        handoff pump where the stream stands; the slot keeps
        prefilling throughout, so a dead pump costs nothing here."""
        from .kv_tier import _sha256
        frag = {"rid": int(st.rid), "partial": True,
                "phase": st.phase,
                "page_size": int(self._kv.page_size),
                "prompt_len": int(st.prompt_len),
                "fill_pos": int(st.fill_pos),
                "filled": int(st.filled),
                "base": int(st.sent_pages),
                "sha256": []}
        if st.phase != "prefill":
            # past activation: nothing streams mid-decode — the full
            # migrate_out ships the balance (and the page beyond
            # sent_pages that activation may have completed)
            return frag, []
        whole = st.filled // self._kv.page_size
        base = st.sent_pages
        if whole <= base:
            return frag, []
        pages = self._kv.slot_pages(slot)[base:whole]
        payloads = [self._spill_payload(p) for p in pages]
        if self._costs is not None:
            self._charge_transfer(
                "page_migrate",
                2 * len(payloads) * self._kv.page_size
                * self._row_nbytes())
        st.sent_pages = whole
        self.stats["handoff_pages_out"] += len(payloads)
        frag["sha256"] = [_sha256(p) for p in payloads]
        if self._rec is not None:
            self._rec.record("handoff_partial", rid=st.rid, base=base,
                             pages=len(payloads))
        if st.journey is not None:
            st.journey.event("handoff", at="source", base=base,
                             pages=len(payloads))
        return frag, payloads

    def migrate_finish(self, rid):
        """Commit a migration: the target restored ``rid`` (and owns its
        waiter now), so release the paused slot's pages here — through
        the normal teardown, so the written prompt prefix is DONATED to
        the prefix cache exactly like a finished request's. Counts
        ``server_migrations_total{result="ok"}`` with the pause-to-
        commit wall in ``serving_migration_seconds``. Nothing lands in
        results or failures: like an evacuated rid, the caller owns the
        request now."""
        with self._lock:
            ent = self._migrating.pop(rid, None)
            if ent is None:
                raise MigrationError(
                    f"request {rid} has no migration in flight")
            slot, t0 = ent[0], ent[1]
            st = self._slots[slot]
            if st is not None and st.rid == rid:
                if st.journey is not None:
                    st.journey.event("migrating", at="source",
                                     handoff=True)
                self._release_slot(slot)
            if self._rec is not None:
                self._rec.record("migrate_done", rid=rid)
            self.stats["migrations"] += 1
            if self._tele is not None:
                self._tele.on_migration("ok", t0)
                self._tele.on_cancel(rid)   # lifecycle closed HERE; the
                #                             target counts nothing (no
                #                             submit/admit there either)
                self._pool_gauges()
            self._done_cv.notify_all()

    def migrate_abort(self, rid):
        """Abort a migration and RESUME the paused slot bit-exactly.
        A mid-decode pause re-pushes the pending token, write position,
        and the PRNG key recomputed from the resolved seed
        (``PRNGKey(seed)`` advanced one split per emitted token — the
        identical chain the device carried), exactly as ``_activate``
        primes a fresh slot. A mid-PREFILL pause (empty-``emitted``
        handoff) simply re-queues the slot on the ragged fifo: the
        planner resumes chunking at ``fill_pos`` and activation fires
        here as if no handoff was ever attempted (the shipped-page
        cursor resets so a later handoff re-ships everything). The
        caller degrades to evacuate+replay or simply lets the slot
        keep going here; either way zero pages moved and zero leaked.
        Counts ``{result="fallback"}`` and freezes a postmortem (its
        ``migration`` section carries the in-flight/outcome state).
        Returns False when nothing was in flight for ``rid``."""
        with self._lock:
            ent = self._migrating.pop(rid, None)
            if ent is None:
                return False
            slot, t0, prior = ent
            st = self._slots[slot]
            if st is None or st.rid != rid:
                return False   # torn down behind the pause (hard stop)
            st.sent_pages = 0
            if prior == "prefill":
                st.phase = "prefill"
                if slot not in self._prefill_fifo:
                    self._prefill_fifo.append(slot)
                if not self._fused:
                    self._pending_t[slot] = self.max_cache_len
                # _active stays False until activation, like any
                # admitted mid-prefill slot
            else:
                st.phase = "decode"
                if not self._fused:
                    key = jax.random.PRNGKey(st.seed)
                    if self.do_sample:
                        for _ in range(len(st.emitted)):
                            key, _ = jax.random.split(key)
                    self._pending_key[slot] = key
                    self._pending_tok[slot] = int(st.emitted[-1])
                    self._pending_t[slot] = \
                        st.prompt_len + len(st.emitted) - 1
                self._active[slot] = True
            self.stats["migration_fallbacks"] += 1
            if self._rec is not None:
                self._rec.record("migrate_fallback", rid=rid)
                self._postmortem_locked("migration_fallback")
            if st.journey is not None:
                st.journey.event("migrating", at="source", fallback=True)
            if self._tele is not None:
                self._tele.on_migration("fallback", t0)
            return True

    def _check_restore_state(self, state):
        """Shared ``migrate_in``/``migrate_in_commit`` validation:
        page-size and role gates, phase-aware written-row accounting.
        Returns ``(phase, emitted, prompt_len, budget, written)``;
        every refusal is a typed ``MigrationError`` raised BEFORE any
        allocation."""
        if int(state.get("page_size", self.page_size)) \
                != self.page_size:
            raise MigrationError(
                f"page-size mismatch: source pages are "
                f"{state.get('page_size')} tokens, this pool's are "
                f"{self.page_size} — migration ships pages whole")
        emitted = [int(t) for t in state.get("emitted") or ()]
        prompt_len = int(state["prompt_len"])
        budget = int(state["budget"])
        phase = str(state.get("phase") or "decode")
        if phase == "decode":
            if self.role == "prefill":
                raise MigrationError(
                    "replica role 'prefill' refuses decode-phase "
                    "admissions — hand mid-decode state to a decode "
                    "or hybrid replica")
            if not emitted or len(emitted) >= budget:
                raise MigrationError(
                    "only mid-decode state restores (source sends "
                    "nothing for queued/finished requests)")
            written = prompt_len + len(emitted) - 1
        elif phase == "prefill":
            # the empty-`emitted` handoff (ISSUE 20): a slot still
            # prefilling ships its written prompt prefix; the
            # remaining rows prefill HERE and activation samples the
            # first token from this replica's own ragged launch —
            # bit-exact, because chunk boundaries never change the
            # written rows and the resolved seed travels with them
            if emitted:
                raise MigrationError(
                    "a prefill-phase handoff cannot carry emitted "
                    "tokens (activation would have flipped the slot "
                    "to decode)")
            written = int(state.get("filled") or 0)
            if not 0 <= written <= prompt_len:
                raise MigrationError(
                    f"filled={written} rows outside the prompt "
                    f"({prompt_len} tokens)")
        else:
            raise MigrationError(
                f"phase {phase!r} state does not restore (sources "
                f"send decoding or prefilling slots only)")
        return phase, emitted, prompt_len, budget, written

    def _scatter_pages_locked(self, own, base, payloads):
        """Scatter received page payloads into this pool's pages
        ``own[base : base + len(payloads)]`` — one batched
        ``.at[:, idx].set`` per k/v leaf, laid out per shard on a mesh
        (the ``_restore_match`` mirror of the source's per-shard
        gather). Caller holds the lock and handles rollback."""
        idx = jnp.asarray(np.asarray(
            own[base:base + len(payloads)], np.int32))
        pool = dict(self._caches["pool"])
        for j, name in enumerate(("k", "v")):
            leaf = pool[name]
            # [L, n, pg, kvh, hd]: page payloads stacked on a new
            # pages axis, matching leaf[:, idx]
            val = np.stack([p[j] for p in payloads], axis=1)
            val = val.astype(leaf.dtype)
            if self._pool_shards > 1:
                try:
                    val = jax.device_put(val, leaf.sharding)
                except Exception:
                    pass
            pool[name] = leaf.at[:, idx].set(jnp.asarray(val))
        self._caches = dict(self._caches, pool=pool)

    def _restore_slot_locked(self, slot, state, phase, emitted,
                             prompt_len, budget, written,
                             on_token, journey):
        """Build and prime the restored ``_Slot`` (the shared tail of
        ``migrate_in`` and ``migrate_in_commit``): a decode-phase
        restore resumes the chain exactly where the source paused it;
        a prefill-phase restore re-queues the ragged fifo at
        ``fill_pos`` so the planner finishes the prompt and activation
        fires HERE. Returns the request's NEW rid."""
        rid = self._next_rid
        self._next_rid += 1
        dl = state.get("deadline_s")
        st = _Slot(rid, np.asarray(state["ids"], np.int32),
                   prompt_len, budget, on_token,
                   None if dl is None
                   else self._clock.now() + float(dl))
        st.seed = int(state["seed"])
        st.emitted = list(emitted)
        st.streamed = int(state.get("streamed", 0))
        st.replayed = tuple(int(t) for t in
                            state.get("replayed", ()))
        st.preempts = int(state.get("preempts", 0))
        st.priority = int(state.get("priority", 0))
        st.n_pre = int(state.get("n_pre", 0))
        st.journey = journey
        self._slots[slot] = st
        if phase == "prefill":
            # remaining prompt rows prefill here; the ragged planner
            # picks the slot up next tick and _activate samples the
            # first token from PRNGKey(seed) — the identical chain
            st.phase = "prefill"
            st.fill_pos = st.filled = written
            self._prefill_fifo.append(slot)
            if not self._fused:
                # park the write cursor on the null page until
                # activation, like any admitted mid-prefill slot
                self._pending_t[slot] = self.max_cache_len
        else:
            # prime the decode chain exactly where the source paused
            # it: pending input = last emitted token, write position =
            # the first unwritten row, PRNG key = seed advanced one
            # split per emitted token (greedy never consumes it)
            key = jax.random.PRNGKey(st.seed)
            if self.do_sample:
                for _ in range(len(emitted)):
                    key, _ = jax.random.split(key)
            if self._fused:
                self._host_keys[slot] = np.asarray(key, np.uint32)
            else:
                self._pending_key[slot] = key
                self._pending_tok[slot] = int(emitted[-1])
                self._pending_t[slot] = written
            self._active[slot] = True
        self.stats["migrated_in"] += 1
        if journey is not None:
            if phase == "prefill":
                journey.event("handoff", at="target", slot=slot,
                              filled=written)
            else:
                journey.event("migrating", at="target", slot=slot,
                              tokens=len(emitted))
        if self._tele is not None:
            self._pool_gauges()
        self._done_cv.notify_all()
        return rid

    def migrate_in(self, state, payloads, on_token=None, journey=None):
        """Restore a migrated request into THIS replica and resume it
        mid-chain: fresh pool pages through the normal ``admit_slot``
        path, one batched scatter of the received page payloads, and
        the slot primed exactly as ``_activate`` would have left it at
        this point of the chain — so the token stream continues
        bit-exactly, greedy or seeded-sampled, with ZERO re-prefill
        dispatches for the shipped rows (the scatter is priced as
        ``page_migrate`` bytes, never counted as a prefill).
        Decode-phase state resumes decoding; prefill-phase state (the
        ISSUE-20 empty-``emitted`` handoff) resumes CHUNKING at
        ``fill_pos`` — only the unshipped remainder of the prompt ever
        prefills here. Returns the request's NEW rid (``wait`` on it
        as usual).

        Every refusal is typed and leak-free: an injected
        ``migrate.restore`` fault, a page failing its end-to-end sha256
        check, a geometry/role mismatch, or a pipelined-stream state
        (``base`` > 0 restores through ``migrate_in_begin``/
        ``migrate_in_pages``/``migrate_in_commit``) raises
        ``MigrationError`` BEFORE any allocation; ``OutOfPages`` (no
        free slot / pool exhausted) propagates from the admit; a
        scatter failure rolls the fresh pages back. The source aborts
        and the caller replays — never a request failure."""
        from .kv_tier import _sha256
        with self._lock:
            if self._kv is None:
                raise MigrationError(
                    "cache_backend='dense' has no page pool to restore "
                    "migrated pages into")
            if not self._accepting:
                raise MigrationError(
                    "replica is draining/stopped — not accepting "
                    "migrated requests")
            if self._faults is not None:
                self._faults.check(faults.MIGRATE_RESTORE,
                                   rid=state.get("rid"))
            if int(state.get("base") or 0):
                raise MigrationError(
                    "state carries a page base — a pipelined partial "
                    "stream restores through migrate_in_begin/"
                    "migrate_in_pages/migrate_in_commit, not a "
                    "one-shot migrate_in")
            phase, emitted, prompt_len, budget, written = \
                self._check_restore_state(state)
            if len(payloads) != self._npages_for(written):
                raise MigrationError(
                    f"page-count mismatch: {len(payloads)} payloads for "
                    f"{written} written rows "
                    f"(expected {self._npages_for(written)})")
            for i, want in enumerate(state.get("sha256") or ()):
                if _sha256(payloads[i]) != want:
                    raise MigrationError(
                        f"migrated page {i}/{len(payloads)} failed its "
                        f"end-to-end sha256 check")
            slot = next((s for s in range(self.max_slots)
                         if self._slots[s] is None), None)
            if slot is None:
                raise OutOfPages(
                    f"no free slot for a migrated request "
                    f"(all {self.max_slots} busy)")
            remaining = budget - len(emitted)
            # a prefill restore sizes its extent off the FULL prompt
            # (the unshipped remainder still needs rows), a decode
            # restore off the written rows — both grow as usual under
            # optimistic admission
            extent = self._extent_tokens(
                prompt_len if phase == "prefill" else written,
                remaining)
            own = self._kv.admit_slot(slot, max(written, extent))
            if payloads:
                try:
                    self._scatter_pages_locked(own, 0, payloads)
                except Exception:
                    self._kv.free_slot(slot)
                    raise
            if self._costs is not None and payloads:
                # priced like spill/restore — bytes both ways, zero
                # FLOPs, and NOT a prefill dispatch: the acceptance
                # counter (stats["prefill_dispatches"]) stays frozen
                self._charge_transfer(
                    "page_migrate",
                    2 * len(payloads) * self.page_size
                    * self._row_nbytes())
            rid = self._restore_slot_locked(
                slot, state, phase, emitted, prompt_len, budget,
                written, on_token, journey)
            if self._rec is not None:
                self._rec.record("migrate_in", rid=rid,
                                 pages=len(payloads), phase=phase,
                                 tokens=len(emitted))
            return rid

    # --------------------- pipelined (staged) prefill-handoff restore
    def migrate_in_begin(self, state):
        """Open a PIPELINED restore (disaggregated prefill handoff,
        ISSUE 20): allocate the slot and its full page extent NOW so
        page batches scatter as the source's chunks complete
        (``migrate_in_pages``) and the first decode tick launches the
        moment the commit lands (``migrate_in_commit``) instead of
        after a monolithic gather. ``state`` needs ``ids``/
        ``prompt_len``/``budget``/``page_size``/``seed`` — the
        commit's full state re-verifies everything that matters.
        Returns an opaque transfer handle; ``migrate_in_abort``
        releases every page if the handoff dies mid-stream, so zero
        leaks either way. The placeholder slot counts toward
        ``in_flight`` (it holds real pool pages) but never ticks: it
        is not active, not on the prefill fifo, and has no deadline
        until commit."""
        with self._lock:
            if self._kv is None:
                raise MigrationError(
                    "cache_backend='dense' has no page pool to restore "
                    "migrated pages into")
            if not self._accepting:
                raise MigrationError(
                    "replica is draining/stopped — not accepting "
                    "migrated requests")
            if self._faults is not None:
                self._faults.check(faults.MIGRATE_RESTORE,
                                   rid=state.get("rid"))
            if int(state.get("page_size", self.page_size)) \
                    != self.page_size:
                raise MigrationError(
                    f"page-size mismatch: source pages are "
                    f"{state.get('page_size')} tokens, this pool's "
                    f"are {self.page_size} — migration ships pages "
                    f"whole")
            if self.role == "prefill" and \
                    str(state.get("phase") or "decode") == "decode":
                raise MigrationError(
                    "replica role 'prefill' refuses decode-phase "
                    "admissions — hand mid-decode state to a decode "
                    "or hybrid replica")
            prompt_len = int(state["prompt_len"])
            budget = int(state["budget"])
            slot = next((s for s in range(self.max_slots)
                         if self._slots[s] is None), None)
            if slot is None:
                raise OutOfPages(
                    f"no free slot for a staged restore "
                    f"(all {self.max_slots} busy)")
            own = self._kv.admit_slot(
                slot, self._extent_tokens(prompt_len, budget))
            rid = self._next_rid
            self._next_rid += 1
            st = _Slot(rid, np.asarray(state["ids"], np.int32),
                       prompt_len, budget)
            st.phase = "staging"
            st.fill_pos = st.filled = 0
            st.seed = int(state.get("seed", 0))
            self._slots[slot] = st
            if not self._fused:
                self._pending_t[slot] = self.max_cache_len
            handle = self._next_xfer
            self._next_xfer += 1
            self._staging[handle] = {"slot": slot, "own": list(own),
                                     "rid": rid, "got": set()}
            if self._rec is not None:
                self._rec.record("handoff_begin", rid=rid, slot=slot,
                                 pages=len(own))
            if self._tele is not None:
                self._pool_gauges()
            return handle

    def migrate_in_pages(self, handle, base, payloads, sha256=None):
        """Scatter one pipelined page batch at page index ``base`` of
        the staged restore ``handle`` — the target half of
        ``migrate_out(partial=True)``. Batches may arrive in any
        order; the commit verifies full coverage. Raises
        ``MigrationError`` (unknown handle, sha256 failure, pages
        outside the staged extent) with the staging KEPT — the caller
        decides between retrying and ``migrate_in_abort``."""
        from .kv_tier import _sha256
        with self._lock:
            ent = self._staging.get(handle)
            if ent is None:
                raise MigrationError(
                    f"no staged restore open for handle {handle!r}")
            if sha256:
                for i, want in enumerate(sha256):
                    if _sha256(payloads[i]) != want:
                        raise MigrationError(
                            f"staged page {int(base) + i} failed its "
                            f"end-to-end sha256 check")
            own = ent["own"]
            base = int(base)
            if base < 0 or base + len(payloads) > len(own):
                raise MigrationError(
                    f"staged pages [{base}, {base + len(payloads)}) "
                    f"fall outside the slot's {len(own)}-page extent")
            if payloads:
                self._scatter_pages_locked(own, base, payloads)
                if self._costs is not None:
                    self._charge_transfer(
                        "page_migrate",
                        2 * len(payloads) * self.page_size
                        * self._row_nbytes())
                ent["got"].update(range(base, base + len(payloads)))
                self.stats["handoff_pages_in"] += len(payloads)
            if self._rec is not None:
                self._rec.record("handoff_pages", rid=ent["rid"],
                                 base=base, pages=len(payloads))
            return len(payloads)

    def migrate_in_commit(self, handle, state, payloads=(),
                          on_token=None, journey=None):
        """Close a pipelined restore: scatter the closing batch (the
        full ``migrate_out(..., from_page=...)`` balance, page base in
        ``state["base"]``), verify every page of the written extent
        arrived, and flip the placeholder into a live slot exactly as
        ``migrate_in`` would — prefill-phase state re-queues the
        ragged fifo at ``fill_pos``, decode-phase state resumes the
        chain. Returns the request's NEW rid. Any refusal (coverage
        gap, sha256, role/geometry mismatch, ids drift from the
        ``migrate_in_begin`` state) raises typed with the staging
        kept, so the caller can still ``migrate_in_abort`` — zero
        leaks."""
        from .kv_tier import _sha256
        with self._lock:
            ent = self._staging.get(handle)
            if ent is None:
                raise MigrationError(
                    f"no staged restore open for handle {handle!r}")
            phase, emitted, prompt_len, budget, written = \
                self._check_restore_state(state)
            slot, own = ent["slot"], ent["own"]
            ph = self._slots[slot]
            if ph is None or ph.rid != ent["rid"]:
                raise MigrationError(
                    "staged slot was torn down behind the transfer "
                    "(hard stop) — nothing to commit")
            if prompt_len != ph.prompt_len or budget != ph.budget \
                    or not np.array_equal(
                        np.asarray(state["ids"], np.int32), ph.ids):
                raise MigrationError(
                    "commit state does not match the migrate_in_begin "
                    "request (ids/prompt_len/budget drift)")
            need = self._npages_for(written)
            base = int(state.get("base") or 0)
            if need > len(own):
                raise MigrationError(
                    f"{need} written pages exceed the staged "
                    f"{len(own)}-page extent")
            if base + len(payloads) != need:
                raise MigrationError(
                    f"closing batch [{base}, {base + len(payloads)}) "
                    f"does not reach the written extent ({need} "
                    f"pages)")
            missing = sorted(set(range(base)) - ent["got"])
            if missing:
                raise MigrationError(
                    f"staged restore incomplete: pages {missing} "
                    f"never arrived before the commit")
            for i, want in enumerate(state.get("sha256") or ()):
                if _sha256(payloads[i]) != want:
                    raise MigrationError(
                        f"closing page {base + i} failed its "
                        f"end-to-end sha256 check")
            if payloads:
                self._scatter_pages_locked(own, base, list(payloads))
                if self._costs is not None:
                    self._charge_transfer(
                        "page_migrate",
                        2 * len(payloads) * self.page_size
                        * self._row_nbytes())
            # flip the placeholder into the live slot: _restore_slot
            # mints the rid the waiter sees (the placeholder rid was
            # never returned to anyone)
            self._slots[slot] = None
            self._staging.pop(handle)
            rid = self._restore_slot_locked(
                slot, state, phase, emitted, prompt_len, budget,
                written, on_token, journey)
            if self._rec is not None:
                self._rec.record("handoff_commit", rid=rid,
                                 pages=need, phase=phase)
            return rid

    def migrate_in_abort(self, handle):
        """Tear down a staged restore that will never commit (source
        died, pump failed, router fell back): release every staged
        page straight back to the allocator — no donation, the rows
        may be half-written — and drop the placeholder. Returns False
        when nothing was staged for ``handle`` (idempotent, like
        ``migrate_abort``)."""
        with self._lock:
            ent = self._staging.pop(handle, None)
            if ent is None:
                return False
            slot = ent["slot"]
            st = self._slots[slot]
            if st is not None and st.rid == ent["rid"]:
                self._slots[slot] = None
                self._active[slot] = False
                pages = self._kv.detach_slot(slot)
                if pages:
                    self._kv.release(pages)
            if self._rec is not None:
                self._rec.record("handoff_abort", rid=ent["rid"])
            if self._tele is not None:
                self._pool_gauges()
            return True

    def kill(self, timeout=60.0):
        """Simulate a replica crash (failover drills, chaos suites):
        stop the serve thread NOW and mark the server ``dead``, but —
        unlike ``stop()`` — leave the queue and in-flight slots exactly
        as they are: no failures recorded, no partials flushed. That is
        the state a router finds after a real crash and harvests with
        ``evacuate(flush_partials=True)``. ``start()`` restarts as
        usual."""
        with self._lock:
            self._accepting = False
            self._draining = False
            if self._rec is not None:
                self._rec.record("killed")
                # the crash-scene snapshot the router's harvest will
                # tear apart: queue + slots exactly as the "crash" left
                # them
                self._postmortem_locked("killed")
            self._health.to(DEAD)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                raise RuntimeError(
                    f"serve thread did not stop within {timeout}s (a "
                    f"tick/compile may still be running); call kill() "
                    f"again to re-join")
            self._thread = None
        with self._lock:
            self._done_cv.notify_all()

    def wait(self, rid, timeout=120.0):
        """Block until ``rid`` finishes (requires start()); returns its
        new tokens. Typed reliability failures (``DeadlineExceeded``,
        ``QueueFullError``, ``CircuitOpenError``, ...) are raised
        directly; other per-request errors are wrapped in a
        ``RuntimeError``; a dead serve thread raises for every
        waiter."""
        import time as _time
        deadline = _time.monotonic() + timeout
        with self._done_cv:
            while True:
                if rid in self._results:
                    return self._results.pop(rid)
                if rid in self._failures:
                    e = self._failures.pop(rid)
                    if isinstance(e, ReliabilityError):
                        raise e
                    raise RuntimeError(
                        f"request {rid} failed at admission: {e}") from e
                if self._thread_error is not None:
                    raise RuntimeError(
                        "serve thread died") from self._thread_error
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"request {rid} not finished in {timeout}s")
                self._done_cv.wait(timeout=min(remaining, 1.0))

    @property
    def failures(self):
        """{rid: exception} for requests whose admission failed:
        pending ones (start()/wait() mode — ``wait(rid)`` pops and
        raises each) plus those drained by the last ``run()``."""
        with self._lock:
            return {**self._run_failures, **self._failures}
