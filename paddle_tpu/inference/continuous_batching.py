"""Continuous-batching decode server (slot-based, static shapes).

The reference's serving depth is AnalysisPredictor + the fused-transformer
decode op driven per request (analysis_predictor.h:95,
fused_multi_transformer_op.cu). The TPU-native upgrade is CONTINUOUS
BATCHING: a fixed pool of decode slots steps as ONE batched XLA program
every tick; finished slots are refilled from the queue without stopping
the others. Static shapes throughout (slot count, cache length) — no
recompiles as requests come and go; per-slot positions ride the vector-t
decode step fns (models/generation.py).

Host/device split: the device does batched prefill + batched decode
steps; the host only assigns slots, harvests finished rows, and swaps
new prompts in — O(requests), not O(tokens), host work.
"""
import threading

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import unwrap

__all__ = ["ContinuousBatchingServer"]


class _Slot:
    __slots__ = ("rid", "prompt_len", "budget", "emitted", "on_token",
                 "streamed")

    def __init__(self, rid, prompt_len, budget, on_token=None):
        self.rid = rid
        self.prompt_len = prompt_len
        self.budget = budget          # max_new_tokens remaining
        self.emitted = []
        self.on_token = on_token
        self.streamed = 0             # tokens already sent to on_token

    def stream(self, sink):
        """Queue this slot's unstreamed chunk on ``sink``; the server
        fires callbacks AFTER releasing its lock (a slow or blocking
        callback must not stall decode/submit/cancel)."""
        if self.on_token is None:
            return
        upto = min(len(self.emitted), self.budget)
        if upto > self.streamed:
            sink.append((self.on_token, self.rid,
                         np.asarray(self.emitted[self.streamed:upto],
                                    np.int32)))
            self.streamed = upto


class ContinuousBatchingServer:
    """Serve ``model.generate``-compatible requests through a fixed slot
    pool. Results are bit-identical to a solo ``model.generate`` call —
    greedy trivially (slots are row-wise independent), and sampled
    decoding too: each request carries its own PRNG chain, split in the
    same pattern as ``sample_generate``, so ``submit(..., seed=s)``
    draws exactly what ``generate(..., do_sample=True, seed=s)`` draws.

    >>> srv = ContinuousBatchingServer(model, max_slots=4,
    ...                                max_cache_len=256)
    >>> rid = srv.submit(prompt_ids, max_new_tokens=32)
    >>> outs = srv.run()            # {rid: np.ndarray of new tokens}

    ``cache_backend="paged"`` swaps the dense ``[slots, max_cache_len]``
    KV buffers for a global page pool + per-slot block tables (ragged
    paged attention; ops/pallas/paged_attention.py, inference/
    kv_cache.py): cache HBM and decode attention bandwidth scale with
    ACTUAL sequence lengths, ``num_pages`` (default: worst case, every
    slot maxed out) sizes the pool to the real working set, registered
    prefixes are stored once and page-shared across slots, and tokens
    stay bit-identical to the dense backend. When the pool is full,
    admission waits (FIFO) for a harvest to free pages.

    ``telemetry`` (``paddle_tpu.telemetry.ServerTelemetry``, or ``True``
    for a default one) turns on SLO instrumentation: per-request
    lifecycle spans and TTFT/TPOT/queue-wait histograms, per-tick
    latency/occupancy, page-pool gauges and prefix-cache counters —
    scrape via ``telemetry.MetricsServer(srv.telemetry.registry)``.
    Host-side only; with the default ``telemetry=None`` the hot path
    pays a single attribute check, no locks and no clock reads.
    """

    def __init__(self, model, max_slots=4, max_cache_len=256,
                 do_sample=False, temperature=1.0, top_k=0, top_p=1.0,
                 eos_token_id=None, seed=0, weight_dtype=None,
                 prefill_chunk=None, mesh=None, tick_block=1,
                 cache_dtype=None, cache_backend="dense", page_size=16,
                 num_pages=None, telemetry=None):
        self.model = model
        self.max_slots = int(max_slots)
        self.max_cache_len = int(max_cache_len)
        self.eos_token_id = eos_token_id
        self.do_sample = bool(do_sample)
        self._temperature = float(temperature)
        self._top_k = int(top_k)
        self._top_p = float(top_p)
        self._seed = int(seed)
        self._keys = jnp.zeros((int(max_slots), 2), jnp.uint32)
        # the dense bundle always exists: prefill (and the prefix cache)
        # run on dense batch-1 caches whatever the decode backend is
        self._bundle = model._decode_bundle(max_cache_len, weight_dtype,
                                            mesh, cache_dtype)
        (self._init_caches, self._embed_fn, self._step_fn,
         self._head_fn, self._prefill_jit) = self._bundle
        self._prefill_chunk = prefill_chunk
        self.tick_block = max(1, int(tick_block))

        if cache_backend not in ("dense", "paged"):
            raise ValueError(f"cache_backend must be 'dense' or 'paged', "
                             f"got {cache_backend!r}")
        self.cache_backend = cache_backend
        self._kv = None
        if cache_backend == "paged":
            # decode runs on a global K/V page pool addressed through
            # per-slot block tables (ragged paged attention); the pool —
            # not slots x max_cache_len — is the cache HBM budget, so it
            # can be sized to the ACTUAL token working set
            from .kv_cache import PagedKVCache
            page_size = int(page_size)
            if self.max_cache_len % page_size:
                raise ValueError(
                    f"page_size ({page_size}) must divide max_cache_len "
                    f"({self.max_cache_len})")
            pages_per_slot = self.max_cache_len // page_size
            if num_pages is None:     # worst case: every slot maxed out
                num_pages = self.max_slots * pages_per_slot + 1
            self._paged_bundle = model._decode_bundle(
                max_cache_len, weight_dtype, mesh, cache_dtype,
                cache_backend="paged", page_size=page_size,
                num_pages=int(num_pages))
            self._step_fn = self._paged_bundle[2]
            self._kv = PagedKVCache(int(num_pages), page_size,
                                    self.max_slots, pages_per_slot)
            self._caches = self._paged_bundle[0](self.max_slots)
            self._pinned_pages = 0     # held forever by register_prefix
        else:
            self._caches = self._init_caches(self.max_slots)
        self._tok = jnp.zeros((self.max_slots,), jnp.int32)
        self._t = jnp.zeros((self.max_slots,), jnp.int32)
        self._active = np.zeros((self.max_slots,), bool)   # host-side
        self._slots = [None] * self.max_slots
        self._queue = []          # (rid, ids_np, max_new_tokens)
        self._results = {}
        self._next_rid = 0
        self._decode_jit = None
        self._prefixes = []   # [(ids, cache_rows, last_logits, pages)]
        self.stats = {"prefill_tokens": 0, "prefix_hit_tokens": 0}
        # telemetry (paddle_tpu.telemetry.ServerTelemetry): True builds
        # a default-enabled one; None (default) keeps the hot path at
        # a single attribute check — no locks, no clock reads
        if telemetry is True:
            from ..telemetry import ServerTelemetry
            telemetry = ServerTelemetry()
        self.telemetry = telemetry
        self._tele = telemetry if (telemetry is not None
                                   and telemetry.enabled) else None
        self._failures = {}   # rid -> admission exception (ADVICE r5 #2)
        self._run_failures = {}   # last run()'s drained failures
        # submit()/cancel() may come from request threads while a serve
        # thread drives step(); one lock covers the queue/slot state and
        # a condition on it wakes wait()ers at harvest time
        self._lock = threading.RLock()
        self._done_cv = threading.Condition(self._lock)
        self._stop = threading.Event()
        self._thread = None
        self._thread_error = None
        self._deferred_cbs = []   # (cb, rid, tokens) fired OUTSIDE the lock

    # ------------------------------------------------------ prefix cache
    def register_prefix(self, prefix_ids):
        """Prefill a shared prompt prefix (e.g. a system prompt) ONCE and
        reuse its KV rows for every later request that starts with it —
        admission then only prefills the remainder. Longest registered
        match wins. Returns the prefix length. Safe to call while a
        serve thread is decoding (the lock serializes it against ticks:
        the paged path writes pool pages and takes allocator pages, both
        of which would otherwise race the donating decode program)."""
        ids = np.asarray(unwrap(prefix_ids)).astype(np.int32).reshape(-1)
        T = ids.shape[0]
        if T + 1 > self.max_cache_len:
            raise ValueError(f"prefix ({T}) leaves no room in "
                             f"max_cache_len ({self.max_cache_len})")
        with self._lock:
            for pre_ids, _, _, _ in self._prefixes:
                # idempotent: re-registering (e.g. a client retry) must
                # not re-prefill or pin a second, unreachable page set
                if (pre_ids.shape[0] == T
                        and np.array_equal(pre_ids, ids)):
                    return T
            if self._prefill_chunk:
                # a queued request was bound-checked at submit against
                # the prefixes registered THEN; refuse a new prefix
                # whose remainder-chunk pad would overflow its rows
                # mid-admission (ADVICE r5 #2)
                for item in self._queue:
                    q_ids = item[1]
                    Tq = q_ids.shape[0]
                    if Tq <= T or not np.array_equal(q_ids[:T], ids):
                        continue
                    cur = self._match_prefix(q_ids)
                    if cur is not None and cur[0].shape[0] >= T:
                        continue    # a longer match still wins
                    rpad = self._chunk_pad(Tq - T)
                    if Tq + rpad > self.max_cache_len:
                        raise ValueError(
                            f"registering this {T}-token prefix "
                            f"would pad the queued {Tq}-token "
                            f"request's remainder prefill {rpad} "
                            f"rows past max_cache_len "
                            f"({self.max_cache_len}) — register "
                            f"prefixes before submitting")
            logits, caches1 = self.model._run_prefill(
                self._bundle, ids[None], chunk=self._prefill_chunk)
            self.stats["prefill_tokens"] += T
            if self._tele is not None:
                self._tele.add_prefill_tokens(T)
            rows = jax.tree_util.tree_map(lambda c: c[:, :, :T], caches1)
            pages = []
            if self._kv is not None:
                # store the prefix's FULL pages once in the pool; every
                # slot that hits the prefix points its block table at
                # them (the alloc ref is the registry's hold — they
                # outlive slot churn and pin pool capacity forever)
                nfull = T // self._kv.page_size
                if nfull:
                    pages = self._kv.alloc(nfull)
                    self._pinned_pages += nfull
            self._prefixes.append((ids, rows, logits, pages))
            self._prefixes.sort(key=lambda e: -e[0].shape[0])
            if self._kv is not None and pages:
                # pinning shrinks the pool for everyone else: a queued
                # request that can no longer EVER fit would silently
                # starve the FIFO — refuse the registration instead
                usable = self._kv.num_pages - 1 - self._pinned_pages
                for _, q_ids, q_budget, _, _ in self._queue:
                    q_need = self._request_pages(
                        q_ids, q_budget, self._match_prefix(q_ids))
                    if q_need > usable:
                        self._prefixes = [e for e in self._prefixes
                                          if e[3] is not pages]
                        self._kv.release(pages)
                        self._pinned_pages -= len(pages)
                        raise ValueError(
                            f"registering this {T}-token prefix pins "
                            f"{len(pages)} pages and would strand an "
                            f"already-queued request needing "
                            f"{q_need} of "
                            f"{usable} usable pages — grow num_pages "
                            f"or register prefixes before submitting")
                self._fill_pages(caches1, pages, 0)
            self._pool_gauges()
        return T

    def _chunk_pad(self, seg_len):
        """Rows the chunked prefill pads past ``seg_len`` — zero when
        the segment runs UNCHUNKED (``seg_len <= chunk``:
        generation._run_prefill takes the direct path and writes exactly
        ``seg_len`` rows)."""
        c = self._prefill_chunk
        if not c or seg_len <= c:
            return 0
        return (-seg_len) % c

    def _match_prefix(self, ids):
        for pre_ids, rows, logits, pages in self._prefixes:
            n = pre_ids.shape[0]
            if ids.shape[0] >= n and np.array_equal(ids[:n], pre_ids):
                return pre_ids, rows, logits, pages
        return None

    # ------------------------------------------------------------ queue
    def submit(self, input_ids, max_new_tokens=32, seed=None,
               on_token=None):
        """Queue a prompt; returns a request id. The FIRST generated
        token is produced by the prefill (same contract as generate()).
        ``seed`` drives this request's sampling chain (default: the
        server seed + request id). ``on_token(rid, tokens)`` streams
        each harvested chunk (1..tick_block tokens) as it lands."""
        ids = np.asarray(unwrap(input_ids)).astype(np.int32)
        if ids.ndim == 2:
            if ids.shape[0] != 1:
                raise ValueError("submit() takes one request; batch by "
                                 "calling submit() per row")
            ids = ids[0]
        T = ids.shape[0]
        with self._lock:
            hit = self._match_prefix(ids)
            pad = 0
            if self._prefill_chunk:
                # a registered-prefix hit prefills only the REMAINDER at
                # t0=n, whose own chunk pad can exceed the full-prompt
                # pad (ADVICE r5 #2). Longest match wins at admission,
                # prefixes are never removed, and register_prefix
                # refuses new ones that would strand a queued request —
                # so the CURRENT longest match decides the bound.
                pad = self._chunk_pad(T - hit[0].shape[0]) \
                    if hit is not None else self._chunk_pad(T)
            if max(T + max_new_tokens, T + pad) > self.max_cache_len:
                seg = "prefix-remainder" \
                    if hit is not None and self._prefill_chunk else "prompt"
                raise ValueError(
                    f"prompt ({T}) + max({max_new_tokens} new tokens, "
                    f"{pad} prefill-chunk pad rows on the {seg}) "
                    f"exceeds max_cache_len ({self.max_cache_len})")
            if self._kv is not None:
                # full-extent reservation (prompt + budget): a request
                # that can never fit must fail HERE, not stall the FIFO
                # forever — pool minus prefix-pinned pages, minus the
                # pinned pages this request would itself share
                need = self._request_pages(ids, int(max_new_tokens), hit)
                usable = self._kv.num_pages - 1 - self._pinned_pages
                if need > usable:
                    raise ValueError(
                        f"prompt ({T}) + max_new_tokens "
                        f"({max_new_tokens}) needs {need} pages beyond "
                        f"its prefix hit but only {usable} are not "
                        f"pinned by prefixes — grow num_pages")
            rid = self._next_rid
            self._next_rid += 1
            if seed is None:
                seed = self._seed + rid
            self._queue.append((rid, ids, int(max_new_tokens), int(seed),
                                on_token))
            if self._tele is not None:
                self._tele.on_submit(rid, T, len(self._queue))
        return rid

    def cancel(self, rid):
        """Drop a request: un-queue it, or free its slot mid-decode (the
        partial result is recorded under the rid). Returns True if the
        request was found live."""
        with self._lock:
            return self._cancel_locked(rid)

    def _cancel_locked(self, rid):
        for i, item in enumerate(self._queue):
            if item[0] == rid:
                del self._queue[i]
                if self._tele is not None:
                    self._tele.on_cancel(rid)
                    self._tele.set_queue_depth(len(self._queue))
                return True
        for slot in range(self.max_slots):
            st = self._slots[slot]
            if self._active[slot] and st.rid == rid:
                self._results[rid] = np.asarray(st.emitted[:st.budget],
                                                np.int32)
                self._active[slot] = False
                self._slots[slot] = None
                if self._kv is not None:
                    self._kv.free_slot(slot)
                if self._tele is not None:
                    self._tele.on_cancel(rid)
                    self._pool_gauges()
                return True
        return False

    # ---------------------------------------------------- paged backend
    def _fill_pages(self, caches1, pages, start):
        """Scatter dense batch-1 cache rows [start, start + len(pages) *
        page_size) into the pool at ``pages`` (position order)."""
        if not pages:
            return
        pg = self._kv.page_size
        n = len(pages) * pg
        ids = jnp.asarray(np.asarray(pages, np.int32))

        def seg(c):            # [L, 1, T', h, hd] -> [L, npg, pg, h, hd]
            s = c[:, 0, start:start + n]
            return s.reshape(s.shape[0], len(pages), pg, s.shape[2],
                             s.shape[3])

        pool = jax.tree_util.tree_map(
            lambda p_, c: p_.at[:, ids].set(seg(c).astype(p_.dtype)),
            self._caches["pool"],
            {"k": caches1["k"], "v": caches1["v"]})
        self._caches = dict(self._caches, pool=pool)

    def _sync_block_table(self):
        """Push the host block-table mirror to the device copy the
        decode program reads. Same shape every time — page churn never
        triggers a recompile."""
        if self._kv is not None and self._kv.dirty:
            self._caches = dict(self._caches,
                                bt=jnp.asarray(self._kv.block_table))
            self._kv.dirty = False

    def _pool_gauges(self):
        """Refresh the page-pool occupancy gauges (paged backend)."""
        if self._tele is not None and self._kv is not None:
            used = self._kv.used_pages()
            self._tele.set_pool(self._kv.free_pages(),
                                used - self._pinned_pages,
                                self._pinned_pages)

    def _request_pages(self, ids, budget, hit):
        """Fresh pages a request needs for its FULL extent (prompt +
        budget — reserved at admission so decode-time growth can never
        hit an empty pool mid-flight), net of the shared pages of
        ``hit`` (the caller's ``_match_prefix`` result)."""
        shared = len(hit[3]) if hit is not None else 0
        return -(-(ids.shape[0] + budget) // self._kv.page_size) - shared

    def _head_fits_pool(self):
        """Can the pool admit the request at the head of the queue right
        now? If not it (and everything behind it — FIFO) waits for a
        harvest to free pages."""
        _, ids, budget, _, _ = self._queue[0]
        return self._kv.free_pages() >= self._request_pages(
            ids, budget, self._match_prefix(ids))

    # ------------------------------------------------------- scheduling
    def _admit(self):
        """Fill free slots from the queue (one prefill program each).
        A request whose admission raises is recorded in ``_failures``
        (its waiters get the error) instead of killing the serve thread
        or losing the rest of the queue (ADVICE r5 #2)."""
        for slot in range(self.max_slots):
            if self._active[slot] or not self._queue:
                continue
            if self._kv is not None and not self._head_fits_pool():
                break
            rid, ids, budget, req_seed, on_token = self._queue.pop(0)
            if self._tele is not None:
                self._tele.on_admit(rid, len(self._queue))
            try:
                self._admit_one(slot, rid, ids, budget, req_seed,
                                on_token)
            except Exception as e:
                if self._kv is not None and self._kv.slot_pages(slot):
                    self._kv.free_slot(slot)     # roll back a part-admit
                self._active[slot] = False
                self._slots[slot] = None
                self._failures[rid] = e
                if self._tele is not None:
                    self._tele.on_admission_failure(rid, e)
                self._done_cv.notify_all()
        if self._tele is not None:
            self._pool_gauges()

    def _admit_one(self, slot, rid, ids, budget, req_seed, on_token):
        T = ids.shape[0]
        # per-request prefill at batch 1 (optionally in fixed-size
        # chunks: one compiled program for every prompt length),
        # then scatter into the pool. A registered-prefix hit seeds
        # the caches and prefills only the remainder.
        hit = self._match_prefix(ids)
        pre_pages = []
        if hit is not None:
            pre_ids, rows, pre_logits, pre_pages = hit
            n = pre_ids.shape[0]
            caches1 = jax.tree_util.tree_map(
                lambda full, r: full.at[:, :, :r.shape[2]].set(r),
                self._init_caches(1), rows)
            rest = ids[n:]
            self.stats["prefix_hit_tokens"] += n
            if rest.shape[0]:
                logits, caches1 = self.model._run_prefill(
                    self._bundle, rest[None],
                    chunk=self._prefill_chunk, caches=caches1, t0=n)
                self.stats["prefill_tokens"] += rest.shape[0]
            else:
                logits = pre_logits
        else:
            logits, caches1 = self.model._run_prefill(
                self._bundle, ids[None], chunk=self._prefill_chunk)
            self.stats["prefill_tokens"] += T
        key = jax.random.PRNGKey(req_seed)
        if self.do_sample:
            # same split pattern as sample_generate.run: one split,
            # sample tok0 from the [1, V] prefill logits
            key, sub = jax.random.split(key)
            from .decode_loop import process_logits
            first = int(jax.random.categorical(
                sub, process_logits(logits, self._temperature,
                                    self._top_k, self._top_p),
                axis=-1)[0])
        else:
            first = int(jnp.argmax(logits, -1)[0])
        self._keys = self._keys.at[slot].set(key)
        if self._kv is not None:
            # shared prefix pages join this slot's table by
            # reference (stored once); the FULL extent (prompt +
            # budget) is reserved up front so mid-decode growth can
            # never exhaust the pool; only prompt rows are copied
            pg = self._kv.page_size
            own = self._kv.admit_slot(slot, T + budget, pre_pages)
            n_prompt = -(-T // pg) - len(pre_pages)
            self._fill_pages(caches1, own[:n_prompt],
                             len(pre_pages) * pg)
        else:
            self._caches = jax.tree_util.tree_map(
                lambda pool, one: pool.at[:, slot].set(one[:, 0]),
                self._caches, caches1)
        self._tok = self._tok.at[slot].set(first)
        self._t = self._t.at[slot].set(T)
        self._active[slot] = True
        st = _Slot(rid, T, budget, on_token)
        st.emitted.append(int(first))
        st.stream(self._deferred_cbs)
        self._slots[slot] = st
        if self._tele is not None:
            pre_n = hit[0].shape[0] if hit is not None else 0
            self._tele.on_first_token(rid, T - pre_n, pre_n)

    # ------------------------------------------------------------ steps
    def _build_decode_step(self):
        """One jitted program running ``tick_block`` decode steps per
        host dispatch (lax.scan; emits the [slots, n] token matrix).
        Larger blocks amortize dispatch (the measured relay cost is
        ~8.6 ms/dispatch vs sub-ms chip work) at the price of admission
        latency and ≤n-1 wasted steps on slots that finish mid-block —
        wasted rows write out of bounds (dropped) or above the frontier
        (masked), never corrupting live slots."""
        embed_p, step_p, head_p = (self._embed_fn, self._step_fn,
                                   self._head_fn)
        do_sample = self.do_sample
        temperature, top_k, top_p = (self._temperature, self._top_k,
                                     self._top_p)
        n = self.tick_block

        def one(tok, caches, t, keys):
            x = embed_p(tok, t)
            out, caches = step_p(x, caches, t)
            logits = head_p(out)
            if logits.ndim == 3:
                logits = logits[:, -1]
            if do_sample:
                from .decode_loop import process_logits

                def samp(k, row):
                    # identical draw chain to sample_generate.body:
                    # split this slot's key, sample over its [1, V] row
                    k2, sub = jax.random.split(k)
                    nxt = jax.random.categorical(
                        sub, process_logits(row[None], temperature,
                                            top_k, top_p), axis=-1)[0]
                    return k2, nxt.astype(jnp.int32)

                keys, nxt = jax.vmap(samp)(keys, logits)
            else:
                nxt = jnp.argmax(logits, -1).astype(jnp.int32)
            return nxt, caches, t + 1, keys

        def block(tok, caches, t, keys):
            def body(carry, _):
                carry = one(*carry)
                return carry, carry[0]
            (tok, caches, t, keys), toks = jax.lax.scan(
                body, (tok, caches, t, keys), None, length=n)
            return tok, caches, t, keys, jnp.transpose(toks, (1, 0))

        return jax.jit(block, donate_argnums=(1,))

    def step(self):
        """One server tick: admit waiting requests, run ``tick_block``
        batched decode steps as one program, harvest finished rows.
        Returns the number of active slots after the tick."""
        with self._lock:
            n = self._step_locked()
        self._fire_callbacks()
        return n

    def _fire_callbacks(self):
        """Run streamed-token callbacks collected during locked work.
        Callback exceptions propagate to the step()/run() caller (or the
        serve thread's error slot) without corrupting server state."""
        cbs, self._deferred_cbs = self._deferred_cbs, []
        for cb, rid, toks in cbs:
            cb(rid, toks)

    def _step_locked(self):
        self._admit()
        if not self._active.any():
            if self._tele is not None:     # keep the gauge live when a
                self._tele.set_active_slots(0)   # drained tick skips decode
            return 0
        # harvest BEFORE stepping: a slot whose budget is spent (or that
        # emitted eos at admission) must not decode further
        self._harvest()
        if not self._active.any():
            if self._tele is not None:
                self._tele.set_active_slots(0)
            return 0
        if self._kv is not None:
            # admission reserved each slot's FULL extent (prompt +
            # budget), so no page growth happens mid-flight; writes past
            # a slot's table (wasted block steps of finished/inactive
            # rows) are redirected to the null page and need no coverage
            self._sync_block_table()
        if self._decode_jit is None:
            self._decode_jit = self._build_decode_step()
        tele = self._tele
        n_active = int(self._active.sum())
        t_tick = tele.tick_started() if tele is not None else None
        (self._tok, self._caches, self._t, self._keys,
         toks) = self._decode_jit(self._tok, self._caches, self._t,
                                  self._keys)
        toks = np.asarray(toks)                    # [slots, tick_block]
        decoded = wasted = 0
        for slot in range(self.max_slots):
            if not self._active[slot]:
                continue
            st = self._slots[slot]
            for j in range(toks.shape[1]):
                st.emitted.append(int(toks[slot, j]))
                if self._finished(st):
                    wasted += toks.shape[1] - (j + 1)
                    break              # later block tokens are waste
            decoded += min(j + 1, toks.shape[1])
            st.stream(self._deferred_cbs)
        if tele is not None:
            # np.asarray above synced the dispatch, so the tick time
            # covers host dispatch + device work
            tele.on_tick(t_tick, n_active, decoded)
            if wasted:
                tele.add_wasted_block_tokens(wasted)
            if self._kv is not None:
                # inactive rows still step; their writes go through an
                # all-null block table row straight to the null page
                tele.add_null_writes(
                    (self.max_slots - n_active) * toks.shape[1])
        self._harvest()
        self._admit()
        n = int(self._active.sum())
        if tele is not None:
            tele.set_active_slots(n)
        return n

    def _finished(self, st):
        if len(st.emitted) >= st.budget:
            return True
        return (self.eos_token_id is not None
                and st.emitted[-1] == self.eos_token_id)

    def _harvest(self):
        finished = False
        for slot in range(self.max_slots):
            st = self._slots[slot]
            if self._active[slot] and self._finished(st):
                out = np.asarray(st.emitted[:st.budget], np.int32)
                self._results[st.rid] = out
                self._active[slot] = False
                self._slots[slot] = None
                if self._kv is not None:
                    self._kv.free_slot(slot)
                if self._tele is not None:
                    self._tele.on_finish(st.rid, len(out))
                finished = True
        if finished:
            if self._tele is not None:
                self._pool_gauges()
            self._done_cv.notify_all()

    def run(self, max_ticks=100000):
        """Drive until queue and slots drain; returns {rid: new_tokens}.
        Requests whose admission failed are left out — their exceptions
        are drained into ``failures`` (per run, so records never
        accumulate across runs)."""
        ticks = 0
        while ticks < max_ticks:
            with self._lock:
                if not (self._queue or self._active.any()):
                    break
                self._step_locked()
            self._fire_callbacks()
            ticks += 1
        with self._lock:
            out, self._results = self._results, {}
            self._run_failures, self._failures = self._failures, {}
        return out

    # ------------------------------------------------------ serve thread
    def start(self, idle_sleep=0.005):
        """Run the decode loop on a background thread: submit()/cancel()
        from any thread; collect results with ``wait(rid)``."""
        if self._thread is not None:
            raise RuntimeError("server already started")
        self._stop.clear()
        self._thread_error = None

        def loop():
            import time as _time
            try:
                while not self._stop.is_set():
                    with self._lock:
                        busy = bool(self._queue or self._active.any())
                        if busy:
                            self._step_locked()
                    self._fire_callbacks()
                    if not busy:
                        _time.sleep(idle_sleep)
            except BaseException as e:   # surface to waiters, don't wedge
                with self._lock:
                    self._thread_error = e
                    self._done_cv.notify_all()

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout=60.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                raise RuntimeError(
                    f"serve thread did not stop within {timeout}s (a "
                    f"tick/compile may still be running); call stop() "
                    f"again to re-join")
            self._thread = None

    def wait(self, rid, timeout=120.0):
        """Block until ``rid`` finishes (requires start()); returns its
        new tokens. Raises this request's admission error if it failed,
        or the serve thread's error if the whole thread died."""
        import time as _time
        deadline = _time.monotonic() + timeout
        with self._done_cv:
            while True:
                if rid in self._results:
                    return self._results.pop(rid)
                if rid in self._failures:
                    e = self._failures.pop(rid)
                    raise RuntimeError(
                        f"request {rid} failed at admission: {e}") from e
                if self._thread_error is not None:
                    raise RuntimeError(
                        "serve thread died") from self._thread_error
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"request {rid} not finished in {timeout}s")
                self._done_cv.wait(timeout=min(remaining, 1.0))

    @property
    def failures(self):
        """{rid: exception} for requests whose admission failed:
        pending ones (start()/wait() mode — ``wait(rid)`` pops and
        raises each) plus those drained by the last ``run()``."""
        with self._lock:
            return {**self._run_failures, **self._failures}
