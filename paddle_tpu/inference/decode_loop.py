"""On-device autoregressive decode loops.

The reference serves decode through ``fused_multi_transformer_op.cu``
(/root/reference/paddle/fluid/operators/fused/fused_multi_transformer_op.cu)
driven by a host loop: one kernel launch per generated token. On TPU the
equivalent host loop pays a full dispatch round-trip per token (and over a
remote-execution relay, several milliseconds), while the chip-side work of
one decode step is sub-millisecond — decode becomes dispatch-bound.

The TPU-native design runs the WHOLE decode loop on device as one XLA
program: ``jax.lax.scan`` over positions with the KV caches as loop carry.
Host dispatch is paid once per sequence instead of once per token, and XLA
pipelines the per-step weight streaming. Two entry points:

- ``scan_decode``: generic — scans any ``step_fn(x, caches, t)`` whose
  output feeds the next step (hidden-state loops, benchmark harnesses).
- ``greedy_generate``: token-level — embed → step → head → argmax fed
  back as the next token; returns the generated ids. The static-shape
  analogue of the reference serving loop.
"""
import weakref

import jax
import jax.numpy as jnp

from ..core.tensor import unwrap

__all__ = ["scan_decode", "greedy_generate", "sample_generate",
           "beam_generate", "fsm_generate", "phrases_to_fsm",
           "process_logits"]


def _pure(fn):
    """Adapt a framework-level fn (may return Tensor wrappers) to a pure
    array fn usable as a ``lax.scan`` body."""
    def run(*args):
        out = fn(*args)
        return jax.tree_util.tree_map(unwrap, out)
    return run


# Compiled-program cache. Anchored on the step_fn (or, for bound methods,
# its instance) via weakref so entries die with their owner; the key tuple
# holds strong refs to every function identity the compiled program closed
# over, so an id can never be reused for a stale hit.
_JIT_CACHE = weakref.WeakKeyDictionary()


def _cached_jit(step_fn, key_tail, build):
    anchor = getattr(step_fn, "__self__", step_fn)
    func = getattr(step_fn, "__func__", None)
    try:
        inner = _JIT_CACHE.setdefault(anchor, {})
    except TypeError:        # non-weakrefable callable: no caching
        return build()
    key = (func, *key_tail)
    jit_run = inner.get(key)
    if jit_run is None:
        jit_run = build()
        inner[key] = jit_run
    return jit_run


def scan_decode(step_fn, x0, caches, t0, steps, donate=True):
    """Run ``steps`` decode iterations on device as ONE program.

    ``step_fn(x, caches, t) -> (out, new_caches)`` is one decoder step
    (e.g. a closure over ``incubate.nn.functional.fused_multi_transformer``
    with ``time_step=t``); ``x0`` is the step input ``[B, 1, D]``,
    ``caches`` the static-shape KV buffers, ``t0`` the starting position
    (int). The output of each step becomes the input of the next.

    Returns ``(out, new_caches)`` after ``steps`` iterations. The jitted
    program is cached on ``step_fn``; repeated calls with the same shapes
    recompile nothing.
    """
    pure_step = _pure(step_fn)

    def body(carry, _):
        x, cs, t = carry
        out, cs2 = pure_step(x, cs, t)
        return (out, cs2, t + 1), None

    def run(x0, caches, t0):
        (x, cs, _), _ = jax.lax.scan(
            body, (x0, caches, jnp.asarray(t0, jnp.int32)), None,
            length=steps)
        return x, cs

    jit_run = _cached_jit(
        step_fn, ("scan_decode", steps, donate),
        lambda: jax.jit(run, donate_argnums=(1,) if donate else ()))
    return jit_run(unwrap(x0), jax.tree_util.tree_map(unwrap, caches), t0)


def greedy_generate(embed_fn, step_fn, head_fn, caches, first_token, t0,
                    max_new_tokens, eos_token_id=None):
    """Greedy autoregressive generation as one on-device program.

    Per step: ``x = embed_fn(tok, t)`` → ``out, caches = step_fn(x,
    caches, t)`` → ``tok' = argmax(head_fn(out))``; the loop carries
    ``(tok, caches, t, done)``. Static shapes throughout: exactly
    ``max_new_tokens`` iterations run; once every row has emitted
    ``eos_token_id`` the remaining steps write ``eos`` (XLA cannot break
    early, matching the padded behavior of batched serving).

    ``first_token`` is ``[B]`` int32 (typically the argmax over the last
    prefill logits); ``t0`` the first decode position. Returns
    ``(ids [B, max_new_tokens], caches)``.

    The compiled program is cached on the ``(embed_fn, step_fn, head_fn,
    max_new_tokens, eos_token_id)`` identity — pass STABLE callables (not
    per-request closures) so repeated requests reuse one compile.
    """
    embed_p, step_p, head_p = _pure(embed_fn), _pure(step_fn), _pure(head_fn)

    def body(carry, _):
        tok, cs, t, done = carry
        x = embed_p(tok, t)
        out, cs2 = step_p(x, cs, t)
        logits = head_p(out)
        if logits.ndim == 3:            # [B, 1, V] -> [B, V]
            logits = logits[:, -1]
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        if eos_token_id is not None:
            nxt = jnp.where(done, jnp.int32(eos_token_id), nxt)
            done = done | (nxt == eos_token_id)
        return (nxt, cs2, t + 1, done), tok

    def run(first_token, caches, t0):
        B = first_token.shape[0]
        tok0 = first_token.astype(jnp.int32)
        # the prefill's token counts: an eos-first row is already done
        # and must eos-pad its whole tail, matching sample_generate and
        # the batching server (ADVICE r5 #1)
        done = (tok0 == eos_token_id) if eos_token_id is not None \
            else jnp.zeros((B,), bool)
        carry = (tok0, caches, jnp.asarray(t0, jnp.int32), done)
        (_, cs, _, _), toks = jax.lax.scan(body, carry, None,
                                           length=max_new_tokens)
        return jnp.transpose(toks, (1, 0)), cs   # [B, T_new]

    jit_run = _cached_jit(
        step_fn,
        ("greedy_generate", embed_fn, head_fn, max_new_tokens,
         eos_token_id),
        lambda: jax.jit(run))
    return jit_run(unwrap(first_token),
                   jax.tree_util.tree_map(unwrap, caches), t0)


def process_logits(logits, temperature=1.0, top_k=0, top_p=1.0):
    """Standard sampling filters (reference generation semantics:
    TopKProcess/TopPProcess in the incubate generation utils): scale by
    temperature, keep the top-k logits, then nucleus-filter to the
    smallest set with cumulative probability >= top_p. Filtered entries
    go to -inf; returns filtered logits ready for categorical sampling."""
    logits = logits.astype(jnp.float32)
    if temperature != 1.0:
        logits = logits / jnp.float32(max(temperature, 1e-6))
    neg = jnp.float32(-1e30)
    if top_k and top_k > 0:
        kth = jnp.sort(logits, axis=-1)[..., -int(top_k)][..., None]
        logits = jnp.where(logits < kth, neg, logits)
    if top_p < 1.0:
        sort_idx = jnp.argsort(-logits, axis=-1)
        sorted_logits = jnp.take_along_axis(logits, sort_idx, axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens until cumulative prob exceeds top_p (always keep
        # the first)
        keep_sorted = (cum - probs) < jnp.float32(top_p)
        keep = jnp.zeros_like(keep_sorted).at[
            jnp.arange(logits.shape[0])[:, None], sort_idx].set(keep_sorted)
        logits = jnp.where(keep, logits, neg)
    return logits


def sample_generate(embed_fn, step_fn, head_fn, caches, first_logits, t0,
                    max_new_tokens, key, temperature=1.0, top_k=0,
                    top_p=1.0, eos_token_id=None):
    """Stochastic generation as one on-device program: every token —
    including the first, drawn from ``first_logits`` (the last prefill
    position) — is sampled with ``jax.random.categorical`` after
    temperature/top-k/top-p filtering (``process_logits``). Same loop
    shape and caching rules as ``greedy_generate``; ``key`` is a JAX
    PRNG key carried through the scan. Returns
    ``(ids [B, max_new_tokens], caches)``.
    """
    embed_p, step_p, head_p = _pure(embed_fn), _pure(step_fn), _pure(head_fn)
    temperature = float(temperature)
    top_k = int(top_k)
    top_p = float(top_p)

    def sample(logits, k):
        if logits.ndim == 3:
            logits = logits[:, -1]
        return jax.random.categorical(
            k, process_logits(logits, temperature, top_k, top_p),
            axis=-1).astype(jnp.int32)

    def body(carry, _):
        tok, cs, t, done, key = carry
        x = embed_p(tok, t)
        out, cs2 = step_p(x, cs, t)
        key, sub = jax.random.split(key)
        nxt = sample(head_p(out), sub)
        if eos_token_id is not None:
            nxt = jnp.where(done, jnp.int32(eos_token_id), nxt)
            done = done | (nxt == eos_token_id)
        return (nxt, cs2, t + 1, done, key), tok

    def run(first_logits, caches, t0, key):
        B = first_logits.shape[0]
        key, sub = jax.random.split(key)
        tok0 = sample(first_logits, sub)
        done = jnp.zeros((B,), bool)
        if eos_token_id is not None:
            done = tok0 == eos_token_id
        carry = (tok0, caches, jnp.asarray(t0, jnp.int32), done, key)
        (_, cs, _, _, _), toks = jax.lax.scan(body, carry, None,
                                              length=max_new_tokens)
        return jnp.transpose(toks, (1, 0)), cs

    jit_run = _cached_jit(
        step_fn,
        ("sample_generate", embed_fn, head_fn, max_new_tokens,
         temperature, top_k, top_p, eos_token_id),
        lambda: jax.jit(run))
    return jit_run(unwrap(first_logits),
                   jax.tree_util.tree_map(unwrap, caches), t0, key)


def beam_generate(embed_fn, step_fn, head_fn, caches, first_logits, t0,
                  max_new_tokens, num_beams, eos_token_id=None):
    """Beam search as one on-device program (reference analogue:
    nn.BeamSearchDecoder/dynamic_decode for RNN cells; this is the
    KV-cache transformer version).

    Beams ride the batch dimension: caches replicate to B*K rows, each
    scan step scores K*V continuations per sequence, keeps the top K,
    and REORDERS the cache rows by beam ancestry with a batched gather.
    Finished beams (eos) are frozen by masking their expansion to the
    eos token at zero log-prob. Returns (ids [B, max_new_tokens] of the
    best beam, final scores [B, K]).

    ``caches`` are the PREFILL caches at batch B (they are replicated
    internally); ``first_logits`` [B, V] the last prefill position.
    """
    embed_p, step_p, head_p = _pure(embed_fn), _pure(step_fn), _pure(head_fn)
    K = int(num_beams)

    def run(first_logits, caches, t0):
        B, V = first_logits.shape
        logp0 = jax.nn.log_softmax(
            first_logits.astype(jnp.float32), -1)
        k0 = min(K, V)        # only V first tokens exist; pad the rest
        scores, tok = jax.lax.top_k(logp0, k0)         # [B, k0]
        if k0 < K:
            scores = jnp.concatenate(
                [scores, jnp.full((B, K - k0), -jnp.inf)], axis=1)
            tok = jnp.concatenate(
                [tok, jnp.zeros((B, K - k0), tok.dtype)], axis=1)
        tok = tok.astype(jnp.int32)
        done = (tok == eos_token_id) if eos_token_id is not None \
            else jnp.zeros((B, K), bool)
        # replicate each sequence's cache rows K times -> batch B*K
        caches = jax.tree_util.tree_map(
            lambda c: jnp.repeat(c, K, axis=1), caches)
        hist = jnp.zeros((B, K, max_new_tokens), jnp.int32)
        hist = hist.at[:, :, 0].set(tok)

        def body(carry, step_i):
            tok, cs, t, scores, done, hist = carry
            x = embed_p(tok.reshape(B * K), t)
            out, cs = step_p(x, cs, t)
            logits = head_p(out)
            if logits.ndim == 3:
                logits = logits[:, -1]
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            logp = logp.reshape(B, K, V)
            if eos_token_id is not None:
                # frozen beams may only "emit" eos at zero cost
                frozen = jnp.full((V,), -jnp.inf).at[eos_token_id].set(0.0)
                logp = jnp.where(done[:, :, None], frozen[None, None],
                                 logp)
            total = scores[:, :, None] + logp              # [B, K, V]
            scores, flat_idx = jax.lax.top_k(
                total.reshape(B, K * V), K)
            beam_idx = (flat_idx // V).astype(jnp.int32)   # ancestor
            tok = (flat_idx % V).astype(jnp.int32)
            # reorder ancestry: cache rows, done flags, histories
            rows = (jnp.arange(B)[:, None] * K + beam_idx).reshape(-1)
            cs = jax.tree_util.tree_map(lambda c: c[:, rows], cs)
            done = jnp.take_along_axis(done, beam_idx, axis=1)
            hist = jnp.take_along_axis(
                hist, beam_idx[:, :, None], axis=1)
            hist = jax.lax.dynamic_update_index_in_dim(
                hist, tok, step_i, axis=2)
            if eos_token_id is not None:
                done = done | (tok == eos_token_id)
            return (tok, cs, t + 1, scores, done, hist), None

        carry = (tok, caches, t0.astype(jnp.int32), scores, done,
                 hist)
        (tok, cs, t, scores, done, hist), _ = jax.lax.scan(
            body, carry, jnp.arange(1, max_new_tokens))
        best = jnp.argmax(scores, axis=1)                  # [B]
        ids = jnp.take_along_axis(hist, best[:, None, None],
                                  axis=1)[:, 0]
        return ids, scores

    jit_run = _cached_jit(
        step_fn,
        ("beam_generate", embed_fn, head_fn, max_new_tokens, K,
         eos_token_id),
        lambda: jax.jit(run))
    return jit_run(unwrap(first_logits),
                   jax.tree_util.tree_map(unwrap, caches),
                   jnp.asarray(t0, jnp.int32))


def fsm_generate(embed_fn, step_fn, head_fn, caches, first_logits, t0,
                 max_new_tokens, fsm_mask, fsm_next, start_state=0,
                 do_sample=False, key=None, temperature=1.0, top_k=0,
                 top_p=1.0, eos_token_id=None):
    """Constrained (structured) generation: a token-level finite-state
    machine masks the logits every step, so the output provably matches
    the grammar the automaton encodes (JSON schemas, enumerated
    choices, tool-call formats).

    ``fsm_mask`` [S, V] bool — tokens allowed in each state; ``fsm_next``
    [S, V] int32 — state after emitting each token. The per-row state
    rides the scan carry; masking is a gather + where, so constrained
    decode costs the same one program as unconstrained. The automaton is
    a runtime ARGUMENT of the compiled program (constraints can change
    per request without recompiling). Greedy by default;
    ``do_sample=True`` samples within the allowed set (same filter chain
    as ``sample_generate``). Returns
    ``(ids [B, max_new_tokens], final_states [B])``.
    """
    embed_p, step_p, head_p = _pure(embed_fn), _pure(step_fn), _pure(head_fn)
    temperature = float(temperature)
    top_k = int(top_k)
    top_p = float(top_p)

    def run(first_logits, caches, t0, key, mask_tab, next_tab):
        def pick(logits, state, k):
            if logits.ndim == 3:
                logits = logits[:, -1]
            allowed = mask_tab[state]                 # [B, V]
            logits = jnp.where(allowed, logits.astype(jnp.float32),
                               -jnp.inf)
            if do_sample:
                return jax.random.categorical(
                    k, process_logits(logits, temperature, top_k,
                                      top_p), axis=-1).astype(jnp.int32)
            return jnp.argmax(logits, -1).astype(jnp.int32)

        def body(carry, _):
            tok, cs, t, state, done, k = carry
            x = embed_p(tok, t)
            out, cs2 = step_p(x, cs, t)
            k, sub = jax.random.split(k)
            nxt = pick(head_p(out), state, sub)
            state = next_tab[state, nxt]
            if eos_token_id is not None:
                nxt = jnp.where(done, jnp.int32(eos_token_id), nxt)
                done = done | (nxt == eos_token_id)
            return (nxt, cs2, t + 1, state, done, k), tok

        B = first_logits.shape[0]
        key, sub = jax.random.split(key)
        state0 = jnp.full((B,), start_state, jnp.int32)
        tok0 = pick(first_logits, state0, sub)
        state = next_tab[state0, tok0]
        done = (tok0 == eos_token_id) if eos_token_id is not None             else jnp.zeros((B,), bool)
        carry = (tok0, caches, t0.astype(jnp.int32), state, done, key)
        (_, cs, _, state, _, _), toks = jax.lax.scan(
            body, carry, None, length=max_new_tokens)
        return jnp.transpose(toks, (1, 0)), state

    if key is None:
        key = jax.random.PRNGKey(0)
    jit_run = _cached_jit(
        step_fn,
        ("fsm_generate", embed_fn, head_fn, max_new_tokens, do_sample,
         temperature, top_k, top_p, eos_token_id, start_state),
        lambda: jax.jit(run))
    return jit_run(unwrap(first_logits),
                   jax.tree_util.tree_map(unwrap, caches),
                   jnp.asarray(t0, jnp.int32), key,
                   jnp.asarray(unwrap(fsm_mask), bool),
                   jnp.asarray(unwrap(fsm_next), jnp.int32))


def phrases_to_fsm(phrases, vocab_size, eos_token_id):
    """Build an (fsm_mask, fsm_next) automaton that forces the output to
    be exactly one of ``phrases`` (token-id sequences, e.g. a fixed set
    of tool names or labels) followed by eos — a trie over the phrases.
    State 0 is the root; the accept state allows only eos."""
    import numpy as np
    if not phrases:
        raise ValueError("phrases must be non-empty")
    for ph in phrases:
        if int(eos_token_id) in (int(t) for t in ph):
            raise ValueError(
                f"phrase {list(ph)} contains eos_token_id "
                f"({eos_token_id}); eos terminates phrases and cannot "
                f"appear inside one")
    states = [{}]              # state -> {token: next_state}
    accept = None
    for ph in phrases:
        cur = 0
        for tok in ph:
            nxt = states[cur].get(int(tok))
            if nxt is None:
                states.append({})
                nxt = len(states) - 1
                states[cur][int(tok)] = nxt
            cur = nxt
        # phrase end: route to the shared accept state
        if accept is None:
            states.append({})
            accept = len(states) - 1
        states[cur][int(eos_token_id)] = accept
    states[accept][int(eos_token_id)] = accept   # absorb
    S = len(states)
    mask = np.zeros((S, vocab_size), bool)
    nxt_tab = np.zeros((S, vocab_size), np.int32)
    for s, edges in enumerate(states):
        for tok, n2 in edges.items():
            mask[s, tok] = True
            nxt_tab[s, tok] = n2
    return mask, nxt_tab
