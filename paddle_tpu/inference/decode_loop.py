"""On-device autoregressive decode loops.

The reference serves decode through ``fused_multi_transformer_op.cu``
(/root/reference/paddle/fluid/operators/fused/fused_multi_transformer_op.cu)
driven by a host loop: one kernel launch per generated token. On TPU the
equivalent host loop pays a full dispatch round-trip per token (and over a
remote-execution relay, several milliseconds), while the chip-side work of
one decode step is sub-millisecond — decode becomes dispatch-bound.

The TPU-native design runs the WHOLE decode loop on device as one XLA
program: ``jax.lax.scan`` over positions with the KV caches as loop carry.
Host dispatch is paid once per sequence instead of once per token, and XLA
pipelines the per-step weight streaming. Two entry points:

- ``scan_decode``: generic — scans any ``step_fn(x, caches, t)`` whose
  output feeds the next step (hidden-state loops, benchmark harnesses).
- ``greedy_generate``: token-level — embed → step → head → argmax fed
  back as the next token; returns the generated ids. The static-shape
  analogue of the reference serving loop.
"""
import weakref

import jax
import jax.numpy as jnp

from ..core.tensor import unwrap

__all__ = ["scan_decode", "greedy_generate"]


def _pure(fn):
    """Adapt a framework-level fn (may return Tensor wrappers) to a pure
    array fn usable as a ``lax.scan`` body."""
    def run(*args):
        out = fn(*args)
        return jax.tree_util.tree_map(unwrap, out)
    return run


# Compiled-program cache. Anchored on the step_fn (or, for bound methods,
# its instance) via weakref so entries die with their owner; the key tuple
# holds strong refs to every function identity the compiled program closed
# over, so an id can never be reused for a stale hit.
_JIT_CACHE = weakref.WeakKeyDictionary()


def _cached_jit(step_fn, key_tail, build):
    anchor = getattr(step_fn, "__self__", step_fn)
    func = getattr(step_fn, "__func__", None)
    try:
        inner = _JIT_CACHE.setdefault(anchor, {})
    except TypeError:        # non-weakrefable callable: no caching
        return build()
    key = (func, *key_tail)
    jit_run = inner.get(key)
    if jit_run is None:
        jit_run = build()
        inner[key] = jit_run
    return jit_run


def scan_decode(step_fn, x0, caches, t0, steps, donate=True):
    """Run ``steps`` decode iterations on device as ONE program.

    ``step_fn(x, caches, t) -> (out, new_caches)`` is one decoder step
    (e.g. a closure over ``incubate.nn.functional.fused_multi_transformer``
    with ``time_step=t``); ``x0`` is the step input ``[B, 1, D]``,
    ``caches`` the static-shape KV buffers, ``t0`` the starting position
    (int). The output of each step becomes the input of the next.

    Returns ``(out, new_caches)`` after ``steps`` iterations. The jitted
    program is cached on ``step_fn``; repeated calls with the same shapes
    recompile nothing.
    """
    pure_step = _pure(step_fn)

    def body(carry, _):
        x, cs, t = carry
        out, cs2 = pure_step(x, cs, t)
        return (out, cs2, t + 1), None

    def run(x0, caches, t0):
        (x, cs, _), _ = jax.lax.scan(
            body, (x0, caches, jnp.asarray(t0, jnp.int32)), None,
            length=steps)
        return x, cs

    jit_run = _cached_jit(
        step_fn, ("scan_decode", steps, donate),
        lambda: jax.jit(run, donate_argnums=(1,) if donate else ()))
    return jit_run(unwrap(x0), jax.tree_util.tree_map(unwrap, caches), t0)


def greedy_generate(embed_fn, step_fn, head_fn, caches, first_token, t0,
                    max_new_tokens, eos_token_id=None):
    """Greedy autoregressive generation as one on-device program.

    Per step: ``x = embed_fn(tok, t)`` → ``out, caches = step_fn(x,
    caches, t)`` → ``tok' = argmax(head_fn(out))``; the loop carries
    ``(tok, caches, t, done)``. Static shapes throughout: exactly
    ``max_new_tokens`` iterations run; once every row has emitted
    ``eos_token_id`` the remaining steps write ``eos`` (XLA cannot break
    early, matching the padded behavior of batched serving).

    ``first_token`` is ``[B]`` int32 (typically the argmax over the last
    prefill logits); ``t0`` the first decode position. Returns
    ``(ids [B, max_new_tokens], caches)``.

    The compiled program is cached on the ``(embed_fn, step_fn, head_fn,
    max_new_tokens, eos_token_id)`` identity — pass STABLE callables (not
    per-request closures) so repeated requests reuse one compile.
    """
    embed_p, step_p, head_p = _pure(embed_fn), _pure(step_fn), _pure(head_fn)

    def body(carry, _):
        tok, cs, t, done = carry
        x = embed_p(tok, t)
        out, cs2 = step_p(x, cs, t)
        logits = head_p(out)
        if logits.ndim == 3:            # [B, 1, V] -> [B, V]
            logits = logits[:, -1]
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        if eos_token_id is not None:
            nxt = jnp.where(done, jnp.int32(eos_token_id), nxt)
            done = done | (nxt == eos_token_id)
        return (nxt, cs2, t + 1, done), tok

    def run(first_token, caches, t0):
        B = first_token.shape[0]
        carry = (first_token.astype(jnp.int32),
                 caches,
                 jnp.asarray(t0, jnp.int32),
                 jnp.zeros((B,), bool))
        (_, cs, _, _), toks = jax.lax.scan(body, carry, None,
                                           length=max_new_tokens)
        return jnp.transpose(toks, (1, 0)), cs   # [B, T_new]

    jit_run = _cached_jit(
        step_fn,
        ("greedy_generate", embed_fn, head_fn, max_new_tokens,
         eos_token_id),
        lambda: jax.jit(run))
    return jit_run(unwrap(first_token),
                   jax.tree_util.tree_map(unwrap, caches), t0)
