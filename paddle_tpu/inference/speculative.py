"""Speculative decoding: a small draft model proposes, the target model
verifies a whole block in one forward.

Serving-side counterpart of the reference's fused decode op — but
instead of one target forward per token, each round costs one draft scan
(cheap) plus ONE target forward over ``gamma + 1`` positions, and
accepts ``k + 1`` tokens (the matched draft prefix plus the target's own
token at the first divergence). With greedy acceptance the output is
BIT-IDENTICAL to the target model's own greedy decode — speculation
changes latency, never results.

Cache discipline: neither model rolls anything back. Rejected draft
positions leave stale KV rows ABOVE the accepted frontier; the causal
validity mask (models/generation.py _cached_attend: key position <=
query position) hides them, and the next round's feed overwrites exactly
those rows before they ever become visible.
"""
import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import unwrap, wrap

__all__ = ["speculative_generate"]


def speculative_generate(target, draft, input_ids, max_new_tokens=32,
                         gamma=4, eos_token_id=None, max_cache_len=None,
                         return_stats=False):
    """Greedy speculative decoding (single sequence).

    ``target`` and ``draft`` are CausalLM models sharing a vocabulary
    (any mix of GPT/Llama/Mixtral). ``gamma`` is the draft block length.
    Returns the full sequence (prompt + new tokens), exactly equal to
    ``target.generate(input_ids, max_new_tokens)``; with
    ``return_stats=True`` also a dict with per-round acceptance counts.
    """
    from .decode_loop import greedy_generate

    ids_np = np.asarray(unwrap(input_ids)).astype(np.int32)
    if ids_np.ndim == 1:
        ids_np = ids_np[None]
    if ids_np.shape[0] != 1:
        raise ValueError("speculative_generate is single-sequence; "
                         "batch via the continuous-batching server")
    T0 = ids_np.shape[1]
    if max_cache_len is None:
        max_cache_len = min(target.cfg.max_seq_len,
                            T0 + max_new_tokens + gamma + 1)
    if T0 + max_new_tokens + gamma + 1 > max_cache_len:
        raise ValueError(
            f"prompt ({T0}) + max_new_tokens ({max_new_tokens}) + "
            f"gamma+1 ({gamma + 1}) exceeds max_cache_len "
            f"({max_cache_len}) — the verify block needs headroom")

    t_init, t_embed, t_step, t_head, t_prefill = \
        target._decode_bundle(max_cache_len)
    d_init, d_embed, d_step, d_head, d_prefill = \
        draft._decode_bundle(max_cache_len)

    # prefill both models on the prompt; first token is the target's
    ids_j = jnp.asarray(ids_np)
    t_caches = t_init(1)
    out, t_caches = t_prefill(target._prefill_embed(ids_j, None),
                              t_caches, jnp.int32(0))
    a = int(jnp.argmax(t_head(out[:, -1:])[:, -1], -1)[0])
    d_caches = d_init(1)
    _, d_caches = d_prefill(draft._prefill_embed(ids_j, None),
                            d_caches, jnp.int32(0))

    verify_jit = jax.jit(
        lambda x, caches, t: t_step(x, caches, t), donate_argnums=(1,))

    emitted = [a]
    t = T0                      # next feed position (token `a` sits here)
    accepts = []
    while len(emitted) < max_new_tokens and not (
            eos_token_id is not None and emitted[-1] == eos_token_id):
        # 1) draft proposes gamma tokens from its own caches
        d_ids, d_caches = greedy_generate(
            d_embed, d_step, d_head, d_caches,
            jnp.asarray([emitted[-1]], jnp.int32), t, gamma + 1)
        # greedy_generate emits [a, d1..dgamma]; drop the echo of `a`
        drafts = np.asarray(d_ids)[0, 1:]                 # gamma tokens

        # 2) one target forward over [a, d1..dgamma]
        block = np.concatenate([[emitted[-1]], drafts]).astype(np.int32)
        x = target._prefill_embed(jnp.asarray(block[None]), None, t0=t)
        out, t_caches = verify_jit(x, t_caches, jnp.int32(t))
        m = np.asarray(jnp.argmax(t_head(out), -1))[0]    # gamma+1 preds

        # 3) accept matched prefix + the target's correction token
        k = 0
        while k < gamma and m[k] == drafts[k]:
            k += 1
        new = list(drafts[:k]) + [int(m[k])]
        accepts.append(k)
        emitted.extend(new)
        t += k + 1
        # draft cache rows for accepted tokens were written while
        # drafting; the correction token is fed next round (as `a`).
        # Rows above the frontier are stale-but-masked (see module doc).

    emitted = emitted[:max_new_tokens]
    if eos_token_id is not None and eos_token_id in emitted:
        # match generate()'s static-shape contract: eos-pad the tail
        emitted = emitted[:emitted.index(eos_token_id) + 1]
        emitted += [eos_token_id] * (max_new_tokens - len(emitted))
    full = np.concatenate([ids_np[0], np.asarray(emitted, np.int32)])
    result = wrap(jnp.asarray(full[None]))
    if return_stats:
        return result, {
            "rounds": len(accepts),
            "accepted_per_round": accepts,
            "mean_accepted": float(np.mean(accepts)) if accepts else 0.0,
            "tokens_per_target_forward":
                (len(emitted) / len(accepts)) if accepts else 1.0,
        }
    return result
