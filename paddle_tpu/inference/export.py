"""StableHLO export/import: the serialization format of the inference engine.

Reference analogue: paddle.static.save_inference_model writes __model__
(ProgramDesc protobuf) + params; AnalysisPredictor reloads and optimizes it
(paddle/fluid/inference/api/analysis_predictor.cc). TPU-native: the artifact
is a `jax.export` archive — StableHLO serialized with multi-platform
(cpu+tpu) lowering, weights baked as constants — plus a JSON meta sidecar.
XLA replays the role of the 253-pass analysis pipeline.
"""
from __future__ import annotations

import json
import os

import jax
from jax import export as jax_export
import jax.numpy as jnp
import numpy as np

_PLATFORMS = ("cpu", "tpu")


def _spec_aval(spec, scope=None, prefix=""):
    """InputSpec → aval; dynamic dims (None/-1) become jax.export symbolic
    dimensions so the archive serves any batch size (reference: -1 dims in
    save_inference_model feed targets). ``prefix`` keeps symbols distinct
    per feed — otherwise two feeds' dim-0 would be unified into one symbol."""
    from ..core.dtype import convert_dtype
    dims = list(spec.shape)
    if not any(d is None or d == -1 for d in dims):
        return spec.to_aval()
    names = []
    sym_src = []
    for i, d in enumerate(dims):
        if d is None or d == -1:
            sym_src.append(f"{prefix}_dyn{i}")
        else:
            sym_src.append(str(int(d)))
    shape = jax_export.symbolic_shape(",".join(sym_src), scope=scope)
    return jax.ShapeDtypeStruct(tuple(shape), convert_dtype(spec.dtype))


def _export_fn(fn, example_avals):
    jitted = jax.jit(fn)
    try:
        return jax_export.export(jitted, platforms=_PLATFORMS)(*example_avals)
    except Exception:
        # some primitives lack multi-platform lowering; fall back to native
        return jax_export.export(jitted)(*example_avals)


def _write(path_prefix, exported, feed_names, fetch_names, feed_specs):
    os.makedirs(os.path.dirname(os.path.abspath(path_prefix)), exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    meta = {
        "format": "paddle_tpu-stablehlo-v1",
        "feed_names": feed_names,
        "fetch_names": fetch_names,
        "feed_specs": feed_specs,
    }
    with open(path_prefix + ".pdmeta", "w") as f:
        json.dump(meta, f)


def export_program(path_prefix, program, feed_names, fetch_names, scope):
    """Export a static Program's inference function (weights from scope)."""
    from ..static import _program_infer_fn
    fn = _program_infer_fn(program, feed_names, fetch_names, scope)
    # honor dynamic (-1/None) feed dims declared via st.data: export with
    # symbolic dims, not the placeholder-1 avals baked into the Variable
    sym_scope = jax_export.SymbolicScope()
    avals = []
    for fi, n in enumerate(feed_names):
        var = program.global_block.vars[n]
        spec = getattr(var, "_input_spec", None)
        if spec is not None:
            avals.append(_spec_aval(spec, scope=sym_scope,
                                    prefix=f"f{fi}"))
        else:
            avals.append(var._value)
    exported = _export_fn(fn, avals)
    specs = []
    for n, a in zip(feed_names, avals):
        dims = [d if isinstance(d, int) else -1 for d in a.shape]
        specs.append({"name": n, "shape": dims, "dtype": str(a.dtype)})
    _write(path_prefix, exported, feed_names, fetch_names, specs)


def export_layer(path_prefix, layer, input_spec):
    """Export an eager Layer (jit.save path): params baked as constants."""
    from ..jit import functional_call

    params = layer.raw_params()
    buffers = {n: b._value for n, b in layer.named_buffers()}
    # eval() recurses into sublayers; snapshot every flag so export can't
    # leave dropout/BN sublayers stuck in eval mode mid-training
    modules = [layer] + [m for _, m in getattr(layer, "named_sublayers",
                                               lambda: [])()]
    was_training = [(m, m.training) for m in modules]
    layer.eval()

    def fn(*inputs):
        return functional_call(layer, params, *inputs, buffers=buffers or None)

    avals = []
    feed_names = []
    sym_scope = jax_export.SymbolicScope()
    for i, spec in enumerate(input_spec):
        if hasattr(spec, "to_aval"):
            avals.append(_spec_aval(spec, scope=sym_scope, prefix=f"f{i}"))
            feed_names.append(spec.name or f"input_{i}")
        else:  # a concrete example array/tensor
            v = np.asarray(getattr(spec, "numpy", lambda: spec)())
            avals.append(jax.ShapeDtypeStruct(v.shape, v.dtype))
            feed_names.append(f"input_{i}")
    try:
        exported = _export_fn(fn, avals)
    finally:
        for m, flag in was_training:
            m.training = flag
    specs = [{"name": n,
              "shape": [int(d) if isinstance(d, int) else -1
                        for d in a.shape],
              "dtype": str(a.dtype)} for n, a in zip(feed_names, avals)]
    _write(path_prefix, exported, feed_names, ["output_0"], specs)


class ExportedProgram:
    """Callable handle over a deserialized jax.export archive."""

    def __init__(self, exported, meta):
        self._exported = exported
        self.meta = meta
        self.feed_names = meta["feed_names"]
        self.fetch_names = meta["fetch_names"]
        self.feed_specs = meta["feed_specs"]

    def __call__(self, *inputs):
        vals = [jnp.asarray(np.asarray(x)) for x in inputs]
        out = self._exported.call(*vals)
        return out

    def run(self, feed):
        vals = [feed[n] for n in self.feed_names]
        return self(*vals)


def load_exported(path_prefix):
    with open(path_prefix + ".pdmodel", "rb") as f:
        exported = jax_export.deserialize(f.read())
    with open(path_prefix + ".pdmeta") as f:
        meta = json.load(f)
    prog = ExportedProgram(exported, meta)
    return prog, prog.feed_names, prog.fetch_names
