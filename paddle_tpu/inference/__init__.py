"""paddle_tpu.inference — the deployment/inference engine.

Reference analogue: paddle_infer C++/Python API (Config, create_predictor,
Predictor with zero-copy handles) over AnalysisPredictor
(paddle/fluid/inference/api/analysis_predictor.h:95, ~102K LoC of IR passes
and subgraph engines). TPU-native: the artifact is a serialized StableHLO
program (see export.py); "analysis + optimization" is XLA's own compiler, so
the predictor is a thin, fast handle around a deserialized jax.export call
with host-pinned input/output buffers.
"""
from __future__ import annotations

import numpy as np

from .export import (ExportedProgram, export_layer, export_program,  # noqa: F401
                     load_exported)

__all__ = ["Config", "Predictor", "create_predictor", "Tensor",
           "export_program", "export_layer", "load_exported",
           "convert_to_mixed_precision", "get_version"]


def get_version():
    import paddle_tpu
    return paddle_tpu.__version__


class Config:
    """paddle_infer.Config parity: model path + execution switches. GPU/IR
    switches are accepted for API compatibility; device choice maps to the
    JAX default device and optimization is always on (XLA)."""

    def __init__(self, prog_file=None, params_file=None):
        # paddle convention: Config("path/prefix") or Config(model, params)
        self._path_prefix = None
        if prog_file is not None:
            self._path_prefix = str(prog_file)
            for suf in (".pdmodel", ".pdiparams"):
                if self._path_prefix.endswith(suf):
                    self._path_prefix = self._path_prefix[: -len(suf)]
        self._use_tpu = True
        self._memory_pool_mb = None
        self._enable_profile = False

    def set_model(self, prog_file, params_file=None):
        self.__init__(prog_file, params_file)

    def model_dir(self):
        return self._path_prefix

    def prog_file(self):
        return (self._path_prefix or "") + ".pdmodel"

    # accepted-for-parity switches --------------------------------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._memory_pool_mb = memory_pool_init_size_mb

    def disable_gpu(self):
        pass

    def enable_memory_optim(self):
        pass

    def switch_ir_optim(self, flag=True):
        pass

    def enable_profile(self):
        self._enable_profile = True

    def set_cpu_math_library_num_threads(self, n):
        pass

    def summary(self):
        return {"model": self.prog_file(), "backend": "xla"}


class Tensor:
    """Zero-copy style IO handle (paddle_infer.Tensor parity)."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def copy_from_cpu(self, data):
        self._value = np.ascontiguousarray(data)

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def reshape(self, shape):
        if self._value is not None:
            self._value = self._value.reshape(shape)

    def shape(self):
        return list(self._value.shape) if self._value is not None else None


class Predictor:
    def __init__(self, config):
        self.config = config
        prog, feeds, fetches = load_exported(config._path_prefix)
        self._prog = prog
        self._inputs = {n: Tensor(n) for n in feeds}
        self._outputs = {n: Tensor(n) for n in fetches}

    def get_input_names(self):
        return list(self._inputs)

    def get_output_names(self):
        return list(self._outputs)

    def get_input_handle(self, name):
        return self._inputs[name]

    def get_input_tensor(self, name):
        return self._inputs[name]

    def get_output_handle(self, name):
        return self._outputs[name]

    def get_output_tensor(self, name):
        return self._outputs[name]

    def run(self, inputs=None):
        """Either positional list of np arrays (returns list) or via handles."""
        if inputs is not None:
            outs = self._prog(*inputs)
            return [np.asarray(o) for o in outs]
        vals = [self._inputs[n]._value for n in self._inputs]
        outs = self._prog(*vals)
        flat = outs if isinstance(outs, (list, tuple)) else [outs]
        for t, v in zip(self._outputs.values(), flat):
            t._value = np.asarray(v)
        return True


def create_predictor(config):
    return Predictor(config)


def convert_to_mixed_precision(src_prefix, dst_prefix, mixed_precision="bf16",
                               backend=None, **kwargs):
    """Re-export an inference archive with inputs/constants cast to bf16/fp16
    (reference: paddle.inference.convert_to_mixed_precision)."""
    raise NotImplementedError(
        "re-export the source program under paddle_tpu.amp.auto_cast "
        "instead; StableHLO archives are precision-final")
