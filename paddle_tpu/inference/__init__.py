"""paddle_tpu.inference — the deployment/inference engine.

Reference analogue: paddle_infer C++/Python API (Config, create_predictor,
Predictor with zero-copy handles) over AnalysisPredictor
(paddle/fluid/inference/api/analysis_predictor.h:95, ~102K LoC of IR passes
and subgraph engines). TPU-native: the artifact is a serialized StableHLO
program (see export.py); "analysis + optimization" is XLA's own compiler, so
the predictor is a thin, fast handle around a deserialized jax.export call
with host-pinned input/output buffers.
"""
from __future__ import annotations

import numpy as np

from .export import (ExportedProgram, export_layer, export_program,  # noqa: F401
                     load_exported)

__all__ = ["Config", "Predictor", "create_predictor", "Tensor",
           "export_program", "export_layer", "load_exported",
           "convert_to_mixed_precision", "get_version",
           # serving stack (beyond the reference surface)
           "BatchScheduler", "ContinuousBatchingServer", "HostTier",
           "ReplicaRouter",
           "RouterSupervisor", "ReplicaHost", "RemoteReplica",
           "spawn_replica_host", "placement", "scan_decode",
           "greedy_generate", "sample_generate", "beam_generate",
           "fsm_generate", "phrases_to_fsm", "process_logits",
           "speculative_generate", "export_decode", "load_decode",
           "DeployedGenerator"]


def get_version():
    import paddle_tpu
    return paddle_tpu.__version__


class Config:
    """paddle_infer.Config parity: model path + execution switches. GPU/IR
    switches are accepted for API compatibility; device choice maps to the
    JAX default device and optimization is always on (XLA)."""

    def __init__(self, prog_file=None, params_file=None):
        # paddle convention: Config("path/prefix") or Config(model, params)
        self._path_prefix = None
        if prog_file is not None:
            self._path_prefix = str(prog_file)
            for suf in (".pdmodel", ".pdiparams"):
                if self._path_prefix.endswith(suf):
                    self._path_prefix = self._path_prefix[: -len(suf)]
        self._use_tpu = True
        self._memory_pool_mb = None
        self._enable_profile = False
        self._memory_optim = False

    def set_model(self, prog_file, params_file=None):
        self.__init__(prog_file, params_file)

    def model_dir(self):
        return self._path_prefix

    def prog_file(self):
        return (self._path_prefix or "") + ".pdmodel"

    # functional switches ------------------------------------------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        # device choice delegates to the JAX default device (TPU here);
        # the pool size is XLA's allocator's concern
        self._memory_pool_mb = memory_pool_init_size_mb

    def disable_gpu(self):
        pass

    def enable_memory_optim(self):
        """Reference memory_optimize pass → input-buffer DONATION: the
        predictor's compiled call may reuse feed buffers for outputs."""
        self._memory_optim = True

    def switch_ir_optim(self, flag=True):
        """XLA always optimizes the exported StableHLO; there is no
        unoptimized execution path to switch to — disabling raises
        instead of silently lying (VERDICT r3 #9: no inert switches)."""
        if not flag:
            # no-roadmap: deliberate API refusal, not a scope cut
            raise NotImplementedError(
                "switch_ir_optim(False): XLA compilation cannot run "
                "without its pass pipeline; export the raw StableHLO "
                "(jit.save) to inspect the unoptimized program")

    def enable_profile(self):
        """Per-run wall-time stats exposed via profile_stats()."""
        self._enable_profile = True
        self._profile = {"runs": 0, "total_ms": 0.0}

    def profile_stats(self):
        return dict(getattr(self, "_profile", {"runs": 0, "total_ms": 0.0}))

    def set_cpu_math_library_num_threads(self, n):
        # CPU-backend math threads (reference MKL knob); XLA:CPU reads
        # this at backend init — record for summary()
        self._cpu_threads = int(n)

    def summary(self):
        return {"model": self.prog_file(), "backend": "xla",
                "memory_optim": self._memory_optim,
                "profile": self._enable_profile}


class Tensor:
    """Zero-copy style IO handle (paddle_infer.Tensor parity)."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def copy_from_cpu(self, data):
        self._value = np.ascontiguousarray(data)

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def reshape(self, shape):
        if self._value is not None:
            self._value = self._value.reshape(shape)

    def shape(self):
        return list(self._value.shape) if self._value is not None else None


class Predictor:
    def __init__(self, config):
        self.config = config
        prog, feeds, fetches = load_exported(config._path_prefix)
        self._prog = prog
        if getattr(config, "_memory_optim", False):
            # enable_memory_optim: donate feed buffers to the compiled
            # call so XLA may alias them for outputs (the reference's
            # memory_optimize pass collapsed to buffer donation)
            import jax
            n_in = len(feeds)
            call = prog._exported.call if hasattr(prog, "_exported") \
                else prog
            self._prog = jax.jit(call,
                                 donate_argnums=tuple(range(n_in)))
        self._inputs = {n: Tensor(n) for n in feeds}
        self._outputs = {n: Tensor(n) for n in fetches}

    def get_input_names(self):
        return list(self._inputs)

    def get_output_names(self):
        return list(self._outputs)

    def get_input_handle(self, name):
        return self._inputs[name]

    def get_input_tensor(self, name):
        return self._inputs[name]

    def get_output_handle(self, name):
        return self._outputs[name]

    def get_output_tensor(self, name):
        return self._outputs[name]

    def run(self, inputs=None):
        """Either positional list of np arrays (returns list) or via handles."""
        import time as _time
        t0 = _time.perf_counter() \
            if getattr(self.config, "_enable_profile", False) else None
        if inputs is not None:
            outs = self._prog(*inputs)
            flat = outs if isinstance(outs, (list, tuple)) else [outs]
            res = [np.asarray(o) for o in flat]
        else:
            vals = [self._inputs[n]._value for n in self._inputs]
            outs = self._prog(*vals)
            flat = outs if isinstance(outs, (list, tuple)) else [outs]
            for t, v in zip(self._outputs.values(), flat):
                t._value = np.asarray(v)
            res = True
        if t0 is not None:
            self.config._profile["runs"] += 1
            self.config._profile["total_ms"] += \
                (_time.perf_counter() - t0) * 1e3
        return res


def create_predictor(config):
    return Predictor(config)


def convert_to_mixed_precision(src_prefix, dst_prefix, mixed_precision="bf16",
                               backend=None, model=None, input_spec=None,
                               **kwargs):
    """Re-export an inference archive in mixed precision (reference:
    paddle.inference.convert_to_mixed_precision, which rewrites the saved
    __model__ program's var dtypes).

    Two paths:
    - ``model`` given (the Layer the archive was exported from, or any
      equivalent): full conversion — parameters are cast to the target
      dtype and a fresh archive is exported to ``dst_prefix``.
    - archive-only: the serialized StableHLO constants are precision-
      final, so the converted archive wraps the original computation with
      inputs/outputs cast to the target dtype (activation-boundary mixed
      precision); weights keep their stored dtype.
    """
    import json

    import jax
    import jax.numpy as jnp

    from ..core.dtype import convert_dtype
    from .export import _export_fn, _write, export_layer, load_exported

    dt = convert_dtype({"bf16": "bfloat16", "fp16": "float16",
                        "float16": "float16",
                        "bfloat16": "bfloat16"}.get(mixed_precision,
                                                    mixed_precision))
    dt = jnp.dtype(dt)
    if model is not None:
        import copy
        m = copy.deepcopy(model)
        m.astype(dt.name)
        if input_spec is None:
            # reuse the source archive's feed specs
            with open(src_prefix + ".pdmeta") as f:
                meta = json.load(f)
            from ..static import InputSpec
            input_spec = [InputSpec(shape=s["shape"], dtype=s["dtype"],
                                    name=s["name"])
                          for s in meta["feed_specs"]]
        export_layer(dst_prefix, m, input_spec)
        return dst_prefix

    prog, feed_names, fetch_names = load_exported(src_prefix)
    in_avals = prog._exported.in_avals

    def mixed(*xs):
        out = prog._exported.call(*xs)
        cast = lambda t: (t.astype(dt)
                          if jnp.issubdtype(t.dtype, jnp.floating) else t)
        return jax.tree_util.tree_map(cast, out)

    # feeds keep the original dtypes (reference semantics: fp32 feeds,
    # reduced-precision compute/outputs); the serialized constants are
    # precision-final, so this path converts the activation boundary only
    exported = _export_fn(mixed, list(in_avals))
    specs = [{"name": n, "shape": [int(d) if isinstance(d, int) else -1
                                   for d in a.shape],
              "dtype": str(a.dtype)}
             for n, a in zip(feed_names, in_avals)]
    _write(dst_prefix, exported, feed_names, fetch_names, specs)
    return dst_prefix


from .serving import BatchScheduler  # noqa: E402  (reference serving surface)
from .decode_loop import (scan_decode, greedy_generate,  # noqa: E402,F401
                          sample_generate, beam_generate, fsm_generate,
                          phrases_to_fsm, process_logits)
from .continuous_batching import ContinuousBatchingServer  # noqa: E402,F401
from .kv_tier import HostTier  # noqa: E402,F401
from .router import ReplicaRouter, RouterSupervisor  # noqa: E402,F401
from .remote import (ReplicaHost, RemoteReplica,  # noqa: E402,F401
                     spawn_replica_host)
from . import placement  # noqa: E402,F401  (disaggregated serving policy)
from .speculative import speculative_generate  # noqa: E402,F401
from .deploy_decode import (export_decode, load_decode,  # noqa: E402,F401
                            DeployedGenerator)
