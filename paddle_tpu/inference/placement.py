"""Disaggregated prefill/decode placement (ISSUE 20, ROADMAP item 4).

The fleet stops being N interchangeable replicas and becomes a PLACED,
phase-specialized system: prefill-specialist replicas run ragged
prefill (the flexible-query-length kernel path) and ship finished
prompt pages to decode specialists over the existing page-frame
handoff, so long prefills stop stealing decode ticks and inflating
TPOT. This module holds the placement brain the router calls into:

- **roles** — ``replica_role()`` reads a replica's ``role`` attribute
  (local servers) or heartbeat digest (``RemoteReplica``), defaulting
  to ``"hybrid"`` for pre-role replicas so mixed-version fleets route
  safely;
- **phase routing** — ``request_phase()`` splits fresh prompts by
  length (short prompts decode-local, no pointless hop) and
  ``order_for_phase()`` rewrites a candidate order for the phase,
  with the full degradation ladder: prefill specialists first for
  long prompts but ANY serving replica as fallback, and decode
  candidates keep prefill specialists only when nothing else serves
  (all-specialists-down degrades to hybrid routing, never failure);
- **handoff targeting** — ``order_handoff_targets()`` ranks decode
  targets by prefix affinity over the existing sketches, then pool
  headroom (free + reclaimable cached pages), then load.

The pump that drives one pipelined handoff lives on the router
(``ReplicaRouter._run_handoff``) because it mutates routes; the
policy decisions all resolve here.
"""

ROLES = ("prefill", "decode", "hybrid")

__all__ = ["ROLES", "replica_role", "request_phase", "order_for_phase",
           "order_handoff_targets", "pool_headroom",
           "normalize_placement"]


def normalize_placement(name):
    """Validate a router ``placement=`` value. ``None``/"affinity" is
    the legacy load/affinity routing (returned as None so the router's
    hot path stays one ``is None`` check); ``"disaggregated"`` turns
    phase-aware placement on."""
    if name in (None, "affinity"):
        return None
    if name == "disaggregated":
        return "disaggregated"
    if name == "cross-datacenter":
        raise NotImplementedError(
            "placement='cross-datacenter' is not wired yet: the "
            "pipelined page handoff assumes one datacenter's flat "
            "network — a WAN hop needs bandwidth-aware frame "
            "scheduling (batch pages by link budget, overlap chunk "
            "streams behind prefill ticks) and locality-tiered "
            "specialist pools; ROADMAP item 4 follow-on")
    raise ValueError(
        f"placement must be None, 'affinity', 'disaggregated' or "
        f"'cross-datacenter', got {name!r}")


def replica_role(rep):
    """A replica's placement role, defaulting unknown/missing/legacy
    values to ``"hybrid"`` — the router must never KeyError routing a
    pre-ISSUE-20 replica."""
    role = getattr(rep, "role", None)
    return role if role in ROLES else "hybrid"


def request_phase(ids, min_prefill_tokens):
    """Which phase a FRESH prompt routes by: long prompts are prefill
    work (place on a specialist, hand off for decode), short prompts
    skip the hop and decode wherever they land."""
    n = int(ids.shape[0]) if hasattr(ids, "shape") else len(ids)
    return "prefill" if n >= int(min_prefill_tokens) else "decode"


def order_for_phase(order, replicas, phase):
    """Rewrite a router candidate order (already affinity/load sorted)
    for a placement phase.

    ``phase="prefill"``: prefill specialists first (stable within each
    group), every other serving replica kept as the degradation tail —
    an all-specialists-down fleet still serves, hybrid-style.

    ``phase="decode"``: prefill specialists are EXCLUDED while any
    non-prefill replica serves (decode work on a specialist defeats
    the point), but kept when they are all that remains — degraded
    beats down."""
    if phase == "prefill":
        pref = [i for i in order
                if replica_role(replicas[i]) == "prefill"]
        rest = [i for i in order if i not in pref]
        return pref + rest
    rest = [i for i in order
            if replica_role(replicas[i]) != "prefill"]
    return rest if rest else list(order)


def pool_headroom(rep):
    """Pages a replica could give a handed-off request RIGHT NOW: free
    pages plus reclaimable cached (prefix-tree) pages. 0 for a dense
    backend or an unreachable host — such a target sorts last, never
    crashes the scan."""
    try:
        bal = rep.pool_balance()
    except Exception:
        return 0
    if bal is None:
        return 0
    return int(bal[0]) + int(bal[3])   # free + cached


def order_handoff_targets(order, replicas, aff):
    """Rank decode-handoff targets: prefix affinity over the existing
    sketches first (the handed-off prompt's pages may already be
    cached there), then pool headroom (the pages need a home), then
    the incoming order (load). ``order`` should already be
    phase-filtered (``order_for_phase(..., "decode")``)."""
    head = {i: pool_headroom(replicas[i]) for i in order}
    pos = {i: k for k, i in enumerate(order)}
    return sorted(order,
                  key=lambda i: (-aff.get(i, 0), -head[i], pos[i]))
