"""Automatic prefix caching: a radix tree of KV pages with LRU eviction.

The paged serving stack (``PagedKVCache`` + ragged paged-attention
decode) already stores a request's KV state in refcounted pool pages,
but PR 1 only REUSED them when an operator called ``register_prefix``
up front — and those pages were pinned forever. This module makes
prefix reuse automatic and bounded, the way production TPU serving
stacks do (Ragged Paged Attention, PAPERS.md): cache residency becomes
a managed resource instead of an operator chore.

Structure: a radix/trie index over token IDs at PAGE granularity. Each
node is one pool page; its key is the ``page_size``-token tuple that
page holds, its children are the pages that can follow it. A path from
the root therefore spells a page-aligned token prefix, and the pages
along the path are exactly the KV state of that prefix — matching is a
dict walk, O(matched pages).

Lifecycle:

- ``donate()``: a finished request's FULL prompt pages (every token in
  the page is a prompt token — partial tail pages and decode-budget
  pages are just freed) are adopted into the tree instead of being
  returned to the free list. Pages whose node already exists are
  deduplicated (the duplicate is released); the rest transfer their
  refcount to the tree. Identical prompts therefore cost one page set
  no matter how often they are served.
- ``lookup()``: the longest cached page run matching a new prompt. The
  server attaches those pages to the slot by reference (``admit_slot``
  shares them exactly like registered-prefix pages) and prefills only
  the remainder — no API change, no operator involvement.
- ``evict()``: whenever the allocator runs short, unpinned cached
  pages are evicted least-recently-used first, LEAF first (a parent
  page is meaningless without the chain below it gone — and a child
  unreachable without its parent), refcount-1 only (the tree's own
  hold; a page a live slot shares is untouchable), ties broken by
  insertion order so two runs evict identically. The cache soaks up
  idle pool capacity and shrinks to nothing under load, with zero
  correctness impact — eviction only ever forgets REUSABLE state.
- ``extend_pinned()``: ``register_prefix`` entries live in the same
  tree as pinned nodes — never evicted, and deduplicated against
  already-donated pages.

Chaos hooks (reliability.FaultInjector): ``prefix.donate`` faults
abandon the insert before any state changes (the caller frees the
pages — the cache loses an entry, never a page); ``prefix.evict``
faults abort that reclaim attempt (the allocator then reports
OutOfPages and admission defers to the next tick). Both paths are
leak-free by construction and asserted so under fault storms in
tests/test_prefix_cache.py.

Host-side only, mutated exclusively under the server lock.

Tiered residency (ISSUE 17): with a ``kv_tier.HostTier`` attached,
eviction becomes SPILL-TO-HOST instead of drop. A demoted node stays
in the tree — same key, same fingerprint (so ``sketch()`` keeps
advertising the run to the router) — but its ``page`` becomes None and
``host`` holds the tier entry with the page's checksummed K/V payload.
Demotion goes bottom-up (a node is demoted only once it has no hot
descendant), so every root-to-leaf path is a HOT prefix followed by a
HOST suffix; ``lookup()`` returns the full run and the server restores
the host suffix into freshly-allocated pool pages before prefill.
Host entries are forgotten for real only at the bottom of the
hierarchy: when the tier's byte budget overflows, the LRU host LEAF
entries leave the tree (``_host_shrink``). One LRU clock
(``last_used``/``seq``) orders both tiers.

Mesh contract (ISSUE 16, sharded paged serving): the tree indexes
PAGE IDS, and on a mesh the pool arrays those ids address are sharded
on the kv-head dimension — so every cached page's K/V state is
automatically split across the shards exactly like live pages, while
the tree, refcounts, pins and LRU order stay host-side and GLOBAL.
Donate/lookup/evict and ``register_prefix`` therefore need no mesh
branch at all: a cached-prefix hit attaches the same page ids on every
shard, and per-shard cache residency is balanced by construction
(asserted in tests/test_sharded_paged_serving.py).
"""
import numpy as np

from ..reliability.faults import PREFIX_DONATE, PREFIX_EVICT

__all__ = ["PrefixCache", "PrefixMatch", "prefix_fingerprints"]

# root value of the rolling fingerprint chain (sketch()/
# prefix_fingerprints must agree on it for membership tests to work)
_SKETCH_ROOT = 0


def prefix_fingerprints(ids, page_size, max_tokens=None):
    """Rolling fingerprints of the page-aligned prefixes of ``ids``:
    entry ``k`` identifies the first ``(k + 1) * page_size`` tokens.
    Built with the same chain as ``PrefixCache.sketch()``, so
    ``fps[k] in sketch`` answers "does that replica's radix tree hold
    this exact page-aligned prefix?" with no tree (or device) access —
    the router's affinity signal. ``max_tokens`` caps the covered
    prefix (the server matches at most ``T - 1`` tokens so the
    remainder prefill still emits first-token logits). Int-tuple
    hashing is unsalted in CPython, so fingerprints are stable across
    processes with the same token stream."""
    ids = np.asarray(ids).reshape(-1)
    n = len(ids) if max_tokens is None else min(len(ids), int(max_tokens))
    pg = int(page_size)
    out, fp = [], _SKETCH_ROOT
    for i in range(n // pg):
        fp = hash((fp, tuple(int(x) for x in ids[i * pg:(i + 1) * pg])))
        out.append(fp)
    return out


class _Node:
    """One cached page: ``key`` is the page's token tuple, ``page`` its
    pool id. ``last_used``/``seq`` order eviction (LRU, then insertion
    order); ``pinned`` marks register_prefix entries; ``fp`` is the
    node's rolling path fingerprint (see ``sketch()``). A HOST-resident
    node (demoted by eviction) has ``page is None`` and ``host`` set to
    its ``kv_tier.HostEntry``; exactly one of the two is ever set."""

    __slots__ = ("key", "page", "parent", "children", "pinned",
                 "last_used", "seq", "fp", "host")

    def __init__(self, key, page, parent, fp=0):
        self.key = key
        self.page = page
        self.parent = parent
        self.children = {}
        self.pinned = False
        self.last_used = 0
        self.seq = 0
        self.fp = fp
        self.host = None


class PrefixMatch:
    """A ``lookup()`` result: ``tokens`` (= ``len(pages) * page_size``)
    of the prompt are already cached in ``pages`` (position order).
    ``nodes`` is the matched tree path — pass it back to ``use()`` when
    the match is actually taken so LRU sees the reuse."""

    __slots__ = ("tokens", "pages", "nodes", "_page_size")

    def __init__(self, nodes, page_size):
        self.nodes = nodes
        self.pages = [n.page for n in nodes]
        self.tokens = len(nodes) * page_size
        self._page_size = page_size

    def shrink(self):
        """The same match minus its last page (None when empty) — the
        server trims a match whose remainder would overflow the
        prefill-chunk pad bound."""
        if len(self.nodes) <= 1:
            return None
        return PrefixMatch(self.nodes[:-1], self._page_size)

    def hot_len(self):
        """Leading nodes that are device-resident RIGHT NOW — the
        shared run an admission can take without a restore; everything
        after is the host suffix (demotion is bottom-up, so the split
        is always prefix/suffix). ``pages``/``tokens`` are snapshots
        from construction: after restoring/promoting nodes, build a
        fresh ``PrefixMatch`` from the same nodes."""
        for i, n in enumerate(self.nodes):
            if n.page is None:
                return i
        return len(self.nodes)


class PrefixCache:
    """Radix-tree index of cached prefix pages over one ``PagedKVCache``.

    Page ownership: every node holds exactly ONE allocator reference to
    its page. Slots that reuse a cached page take their own reference
    (``admit_slot(shared_pages=...)``), so ``kv.refcount(page) > 1``
    means "in use by a live slot" and blocks eviction. ``pinned_pages``
    / ``cached_pages`` partition the tree for pool accounting
    (``pool_balance()`` / the ``kv_pool_pages`` gauge).
    """

    def __init__(self, kv, fault_injector=None, host_tier=None,
                 spill=None):
        self.kv = kv
        self.page_size = kv.page_size
        # second tier (kv_tier.HostTier): eviction demotes instead of
        # dropping. ``spill(page_id) -> payload arrays`` is the
        # server-bound device gather (per-shard on a mesh); without
        # both, eviction behaves exactly as before.
        self._tier = host_tier \
            if (host_tier is not None and host_tier.enabled
                and spill is not None) else None
        self._spill = spill
        self._root = _Node(None, None, None, fp=_SKETCH_ROOT)
        # fingerprint index maintained INCREMENTALLY alongside the tree
        # (one rolling hash per node) and published as an immutable
        # snapshot, so a router can read sketch() without the server
        # lock — a serve thread holds that lock for whole ticks. The
        # snapshot is republished in BATCHES (flush_sketch, once per
        # server tick / pin / evacuation), not per mutation: a
        # multi-slot harvest pays one O(tree) copy, not one per slot.
        self._sketch = set()
        self._sketch_dirty = False
        self.sketch_snapshot = frozenset()
        self._tick = 0          # logical LRU clock (bumped per touch)
        self._seq = 0           # insertion order, the deterministic tie-break
        self._protected = frozenset()   # node ids shielded from eviction
        self._faults = fault_injector
        self.pinned_pages = 0   # nodes register_prefix pinned (never evicted)
        self.cached_pages = 0   # unpinned nodes (evictable when refcount 1)
        self.host_pages = 0     # host-resident nodes (no device page)
        # cumulative stats (the server mirrors these into telemetry)
        self.donated_pages_total = 0   # new nodes created by donate()
        self.dedup_pages_total = 0     # donated pages already in the tree
        self.evicted_pages_total = 0

    # ---------------------------------------------------------- matching
    def _page_keys(self, ids, npages):
        ids = np.asarray(ids).reshape(-1)
        pg = self.page_size
        return [tuple(int(x) for x in ids[i * pg:(i + 1) * pg])
                for i in range(npages)]

    def _walk(self, ids, npages):
        """Existing tree path for the first ``npages`` pages of ``ids``
        (possibly shorter — the longest run present). Keys are built
        lazily: a miss at page k costs O(k) token tuples, not
        O(npages) — this runs on every admission attempt, misses
        included."""
        ids = np.asarray(ids).reshape(-1)
        pg = self.page_size
        node, run = self._root, []
        for i in range(npages):
            key = tuple(int(x) for x in ids[i * pg:(i + 1) * pg])
            child = node.children.get(key)
            if child is None:
                break
            run.append(child)
            node = child
        return run

    def lookup(self, ids, max_tokens):
        """Longest cached page-aligned prefix of ``ids`` covering at
        most ``max_tokens`` tokens, or None. Pure — no LRU touch —
        so admission-feasibility checks can probe speculatively; call
        ``use()`` on the match when it is actually taken."""
        npages = min(int(max_tokens), len(np.asarray(ids).reshape(-1))) \
            // self.page_size
        if npages <= 0:
            return None
        run = self._walk(ids, npages)
        if not run:
            return None
        return PrefixMatch(run, self.page_size)

    def node_run(self, ids):
        """Existing HOT nodes covering ``ids`` (which must be
        page-aligned) — register_prefix adopts these instead of
        re-allocating. The run stops at the first host-resident node:
        a pinned entry computes (and pins) its own fresh pages from
        there, replacing the spilled payloads (``extend_pinned``)."""
        ids = np.asarray(ids).reshape(-1)
        run = self._walk(ids, len(ids) // self.page_size)
        for i, n in enumerate(run):
            if n.page is None:
                return run[:i]
        return run

    def _touch(self, node):
        self._tick += 1
        node.last_used = self._tick

    def use(self, match):
        """Mark a taken match as just-used (root-to-leaf, so deeper
        pages read as more recent and fall last under LRU)."""
        for node in match.nodes:
            self._touch(node)

    # ---------------------------------------------------------- donation
    def donate(self, ids, pages, prompt_len, cold=False):
        """Adopt a released slot's page list: full prompt pages become
        (or refresh) tree nodes, everything else — the partial prompt
        tail and the decode budget — is released. Takes ownership of
        EVERY reference the caller held on ``pages``: existing nodes
        absorb the duplicate (released), new nodes keep theirs. Returns
        the number of newly cached pages.

        ``cold=True`` is the PREEMPTION donation path: the donated run
        enters at the COLD end of the LRU instead of as most-recent —
        new nodes keep ``last_used=0`` (insertion ``seq`` still breaks
        ties deterministically) and existing nodes keep their real
        recency untouched. A preemption victim was chosen as the least
        valuable work in flight, and the very grow that displaced it is
        about to reclaim pages — cold insertion lets that reclaim take
        the victim's pages FIRST while a genuinely hot shared prefix
        among them (an existing, recently-used node) survives. The
        pages stay lookup-able until evicted, so a quickly re-admitted
        victim still auto-hits its own prompt (prefix-cache-assisted
        recompute).

        Raises (``prefix.donate`` fault) strictly BEFORE any state
        changes — on failure the caller still owns all ``pages`` and
        frees them; the tree and refcounts are untouched."""
        if self._faults is not None:
            self._faults.check(PREFIX_DONATE, pages=len(pages))
        nf = min(int(prompt_len) // self.page_size, len(pages))
        node, new = self._root, 0
        for key, page in zip(self._page_keys(ids, nf), pages[:nf]):
            child = node.children.get(key)
            if child is not None and child.page is None:
                # host-resident: the donated page IS this prefix's KV
                # state, recomputed by the slot that just finished —
                # adopt it (a free promotion) and drop the spilled
                # payload instead of ever reading it back
                self._tier.discard(child.host)
                child.host = None
                child.page = page
                self.host_pages -= 1
                self.cached_pages += 1
            elif child is not None:
                # already cached (maybe the very page this slot shared
                # at admission): drop the slot's duplicate reference
                self.kv.release([page])
                self.dedup_pages_total += 1
            else:
                child = _Node(key, page, node, fp=hash((node.fp, key)))
                self._seq += 1
                child.seq = self._seq
                node.children[key] = child
                self._sketch.add(child.fp)
                self.cached_pages += 1
                new += 1
            if not cold:
                self._touch(child)
            node = child
        self.kv.release(pages[nf:])
        self.donated_pages_total += new
        if new:
            self._sketch_dirty = True
        return new

    # ---------------------------------------------------------- eviction
    def _evictable(self, exclude=()):
        """Nodes safe to remove: unpinned, unprotected, refcount 1 (only
        the tree's own hold), and no blocked descendant — an ancestor of
        a pinned/shared/protected page must survive so the chain below
        it stays reachable."""
        ex = {id(n) for n in exclude} | self._protected
        out = []

        def walk(n):
            ok = True
            for ch in n.children.values():
                ok = walk(ch) and ok
            if n.page is None:
                # host-resident: holds no device page — transparent to
                # the sweep (never a candidate, never a blocker; its
                # hot ancestors demote right over it)
                return ok
            ok = (ok and not n.pinned and id(n) not in ex
                  and self.kv.refcount(n.page) == 1)
            if ok:
                out.append(n)
            return ok

        for ch in self._root.children.values():
            walk(ch)
        return out

    def evictable_pages(self, exclude=()):
        """Pages an eviction sweep could free right now — admission
        counts these as available headroom. ``exclude`` holds the
        nodes a pending match is about to take by reference."""
        return len(self._evictable(exclude))

    def protect(self, nodes):
        """Shield ``nodes`` from eviction across an allocator call that
        may reclaim (register_prefix adopting a cached run must not
        have that run evicted out from under it). Pass ``()`` to
        clear."""
        self._protected = frozenset(id(n) for n in nodes)

    def evict(self, need):
        """Free up to ``need`` device pages, least-recently-used leaf
        first (ties by insertion order — fully deterministic). With a
        host tier attached the victim is DEMOTED — payload spilled to
        the tier, node kept (fingerprint and all) with ``page=None`` —
        and only dropped outright when the spill itself fails
        (injected ``tier.spill`` fault / gather error). Either way the
        victim's device page is freed, so the sweep is leak-free under
        fault storms. Returns the number of device pages freed;
        raising (``prefix.evict`` fault) happens strictly before any
        state changes."""
        if self._faults is not None:
            self._faults.check(PREFIX_EVICT, need=int(need))
        safe = set(self._evictable())
        freed = 0
        while freed < int(need):
            # device-leaves: safe nodes with no HOT child (a demoted
            # child stays in the tree, so "no children" is too strong
            # once the tier is on; a hot child not in ``safe`` already
            # disqualified its ancestors in the walk)
            leaves = [n for n in safe
                      if not any(ch.page is not None
                                 for ch in n.children.values())]
            if not leaves:
                break
            victim = min(leaves, key=lambda n: (n.last_used, n.seq))
            safe.discard(victim)
            if self._demote(victim):
                self.kv.release([victim.page])
                victim.page = None
                self.cached_pages -= 1
                self.host_pages += 1
            else:
                # plain drop — the node leaves the tree, taking its
                # (all-host) subtree with it
                self.drop_subtree(victim)
            freed += 1
        if freed:
            self._sketch_dirty = True
        self._host_shrink()
        return freed

    def _demote(self, victim):
        """Try to spill ``victim``'s page payload to the host tier.
        True on success (caller flips the node to host residency);
        False — no tier, injected spill fault, or gather failure —
        means fall back to dropping, with no tier state changed."""
        if self._tier is None:
            return False
        try:
            payload = self._spill(victim.page)
            victim.host = self._tier.put(payload, page=int(victim.page))
        except Exception:
            victim.host = None
            return False
        return True

    def drop_subtree(self, node):
        """Remove ``node`` and everything below it from the tree: hot
        pages go back to the allocator, host entries leave the tier,
        fingerprints leave the sketch. Used for the spill-fault drop
        path and for forgetting a corrupted host run. Returns device
        pages released."""
        if node.parent is not None \
                and node.parent.children.get(node.key) is node:
            del node.parent.children[node.key]
        released = 0
        stack = [node]
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            n.children = {}
            self._sketch.discard(n.fp)
            if n.page is not None:
                self.kv.release([n.page])
                if n.pinned:
                    self.pinned_pages -= 1
                else:
                    self.cached_pages -= 1
                self.evicted_pages_total += 1
                released += 1
            elif n.host is not None:
                self._tier.discard(n.host, evicted=True)
                n.host = None
                self.host_pages -= 1
        self._sketch_dirty = True
        return released

    def _host_shrink(self):
        """The bottom of the hierarchy: while the host tier is over
        its byte budget, its least-recently-used LEAF entries are
        forgotten for real (LRU then insertion order, leaf first —
        the same deterministic order as device eviction)."""
        if self._tier is None or not self._tier.over_budget():
            return
        leaves = []
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if n.page is None and not n.children \
                    and id(n) not in self._protected:
                leaves.append(n)
        while self._tier.over_budget() and leaves:
            victim = min(leaves, key=lambda n: (n.last_used, n.seq))
            leaves.remove(victim)
            del victim.parent.children[victim.key]
            self._sketch.discard(victim.fp)
            self._tier.discard(victim.host, evicted=True)
            victim.host = None
            self.host_pages -= 1
            self._sketch_dirty = True
            p = victim.parent
            if p is not self._root and p.page is None \
                    and not p.children and id(p) not in self._protected:
                leaves.append(p)

    def promote(self, node, page):
        """A restore landed: ``node``'s payload is back in pool page
        ``page`` (the caller transfers its one allocator reference to
        the node — the normal donate ownership contract) and the host
        entry's bytes return to the tier."""
        self._tier.discard(node.host)
        node.host = None
        node.page = page
        self.host_pages -= 1
        self.cached_pages += 1
        self._touch(node)

    # ----------------------------------------------------------- pinning
    def extend_pinned(self, ids, run, own_pages):
        """Commit a ``register_prefix`` entry: pin the existing ``run``
        (adopted donated pages stop being evictable) and append
        ``own_pages`` as fresh pinned nodes for the remaining keys of
        page-aligned ``ids``. Returns the entry's full page list."""
        for nd in run:
            self._touch(nd)
            if not nd.pinned:
                nd.pinned = True
                self.cached_pages -= 1
                self.pinned_pages += 1
        node = run[-1] if run else self._root
        ids = np.asarray(ids).reshape(-1)
        keys = self._page_keys(ids, len(ids) // self.page_size)
        added = False
        for key, page in zip(keys[len(run):], own_pages):
            child = node.children.get(key)
            if child is not None:
                # a host-resident node on this path (node_run stopped
                # above it): the entry's freshly-computed page replaces
                # the spilled payload — promote-by-pin, no restore read
                self._tier.discard(child.host)
                child.host = None
                child.page = page
                self.host_pages -= 1
            else:
                child = _Node(key, page, node, fp=hash((node.fp, key)))
                self._seq += 1
                child.seq = self._seq
                node.children[key] = child
                self._sketch.add(child.fp)
                added = True
            child.pinned = True
            self._touch(child)
            node = child
            self.pinned_pages += 1
        if added:
            self._sketch_dirty = True
        return [n.page for n in run] + list(own_pages)

    # ---------------------------------------------------------- sketching
    def flush_sketch(self):
        """Republish the lock-free snapshot if the tree changed since
        the last flush. The server calls this once per tick (plus at
        register_prefix and evacuation boundaries) — off-tick
        mutations (e.g. a client-thread cancel's donation) surface at
        the next tick, which only staleness-bounds a routing HINT."""
        if self._sketch_dirty:
            self._sketch_dirty = False
            self.sketch_snapshot = frozenset(self._sketch)

    def sketch(self):
        """Host-side fingerprint set of every page-aligned prefix the
        tree currently caches (pinned and unpinned alike) — one rolling
        hash per node, O(nodes) ints, zero device reads. A router keeps
        one sketch per replica and routes a prompt to the replica whose
        sketch covers its longest ``prefix_fingerprints`` run.

        Returns the maintained IMMUTABLE snapshot, so it is safe to
        call WITHOUT the server lock (a serve thread holds that lock
        for whole decode ticks — the router must not queue behind it
        just to pick a destination). A sketch is a ROUTING HINT, not a
        contract: pages may be evicted right after it is read, which
        costs the chosen replica a cache miss, never correctness."""
        return self.sketch_snapshot

    # -------------------------------------------------------- accounting
    def stats(self):
        """Point-in-time tree state + cumulative churn, plain data
        (``/stats`` and postmortem bundles). ``sketch_size`` is the
        live fingerprint count — the size of the affinity signal the
        router reads, which a postmortem wants next to the page
        counts (a dead replica with a big sketch is lost locality the
        fleet will re-prefill)."""
        return {"cached_pages": self.cached_pages,
                "pinned_pages": self.pinned_pages,
                "host_pages": self.host_pages,
                "sketch_size": len(self._sketch),
                "donated_pages_total": self.donated_pages_total,
                "dedup_pages_total": self.dedup_pages_total,
                "evicted_pages_total": self.evicted_pages_total}
