"""Minimal batched-request scheduler over a Predictor.

Reference: the AnalysisPredictor serving surface
(paddle/fluid/inference/api/analysis_predictor.h:95 — zero-copy IO,
multi-stream request execution). TPU-native collapse: one compiled XLA
program serves every request; the scheduler's job is to GROUP pending
requests into a single batched call (the MXU wants batch, and a fixed
batch shape avoids recompiles), then split the outputs back per request.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future, InvalidStateError

import numpy as np

from ..reliability import (DeadlineExceeded, QueueFullError,
                           SchedulerClosed)

__all__ = ["BatchScheduler", "serve_metrics"]


class _Request:
    __slots__ = ("inputs", "future", "n", "t_submit", "deadline")

    def __init__(self, inputs, t_submit=None, deadline=None):
        self.inputs = inputs
        self.future = Future()
        self.n = int(inputs[0].shape[0])    # rows this request contributes
        self.t_submit = t_submit
        self.deadline = deadline            # absolute clock time, or None

    def settle(self, result=None, error=None):
        """Resolve the future, losing gracefully if the other side of a
        close()/worker race settled it first (whoever wins, the waiter
        sees exactly one outcome)."""
        try:
            if error is not None:
                self.future.set_exception(error)
            else:
                self.future.set_result(result)
        except InvalidStateError:
            pass


class BatchScheduler:
    """Group submitted requests into batched runner calls.

    ``runner``: a ``Predictor`` (its positional ``run(list)`` is used) or
    any callable ``f(list_of_stacked_arrays) -> list_of_arrays`` where
    every output keeps the stacked batch on axis 0.

    ``submit(*arrays)`` returns a ``concurrent.futures.Future`` whose
    result is the list of this request's output slices. Requests are
    batched up to ``max_batch_size`` rows; a partially filled batch
    launches after ``max_delay_ms``. Requests whose trailing shapes
    differ batch separately (a shape change would recompile — the
    scheduler never mixes them).

    ``max_queue`` bounds the pending-request count: a full queue REJECTS
    the submit with ``QueueFullError`` instead of growing without bound
    under overload. ``submit(..., deadline_s=)`` bounds waiting: a
    request still queued when its deadline passes fails its future with
    ``DeadlineExceeded`` before any runner time is spent on it.

    ``registry`` (``telemetry.MetricRegistry``) publishes
    ``scheduler_batch_rows`` / ``scheduler_batch_seconds`` /
    ``scheduler_queue_wait_seconds`` histograms and
    ``scheduler_{requests,batches,failures}_total`` counters; with the
    default ``None`` the hot path pays one ``is None`` check.
    """

    def __init__(self, runner, max_batch_size=8, max_delay_ms=5.0,
                 registry=None, clock=None, max_queue=None):
        self._run = (runner.run if hasattr(runner, "run") else runner)
        self.max_batch = int(max_batch_size)
        self.max_delay = float(max_delay_ms) / 1e3
        self.max_queue = None if max_queue is None else int(max_queue)
        self._lock = threading.Condition()
        self._queue = []                    # pending _Request, FIFO
        self._inflight = []                 # popped group the runner holds
        self._closed = False
        self.batches_run = 0                # introspection for tests
        self._m = None
        from ..telemetry.clock import MonotonicClock
        self._clock = clock if clock is not None else MonotonicClock()
        if registry is not None and registry.enabled:
            from ..telemetry.serving import (OCCUPANCY_BUCKETS,
                                             TICK_BUCKETS)
            self._m = {
                "rows": registry.histogram(
                    "scheduler_batch_rows", "Rows per batched call",
                    buckets=OCCUPANCY_BUCKETS),
                "batch_s": registry.histogram(
                    "scheduler_batch_seconds", "One batched runner call",
                    buckets=TICK_BUCKETS),
                "wait_s": registry.histogram(
                    "scheduler_queue_wait_seconds",
                    "submit() to batch launch", buckets=TICK_BUCKETS),
                "requests": registry.counter(
                    "scheduler_requests_total", "Requests submitted"),
                "batches": registry.counter(
                    "scheduler_batches_total", "Batched calls run"),
                "failures": registry.counter(
                    "scheduler_failures_total",
                    "Batched calls that raised"),
            }
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    # ------------------------------------------------------------ client
    def submit(self, *arrays, deadline_s=None):
        arrays = [np.asarray(a) for a in arrays]
        if not arrays:
            raise ValueError("submit() needs at least one input array")
        deadline = None if deadline_s is None \
            else self._clock.now() + float(deadline_s)
        req = _Request(arrays, deadline=deadline)
        with self._lock:
            if self._closed:
                raise SchedulerClosed("scheduler is closed")
            if (self.max_queue is not None
                    and len(self._queue) >= self.max_queue):
                raise QueueFullError(
                    f"scheduler queue holds {len(self._queue)} requests "
                    f"(max_queue={self.max_queue}) — resubmit with "
                    f"backoff")
            if self._m:        # count only ACCEPTED requests
                req.t_submit = self._clock.now()
                self._m["requests"].inc()
            self._queue.append(req)
            self._lock.notify()
        return req.future

    def close(self, timeout=10.0):
        """Stop the worker after it drains the queue. If the worker is
        WEDGED inside a runner call, every still-pending future (queued
        or held by the stuck batch) is failed with ``SchedulerClosed``
        — a waiter must never hang on a scheduler that already gave up
        — and the join timeout is surfaced as ``TimeoutError``."""
        with self._lock:
            self._closed = True
            self._lock.notify()
        self._worker.join(timeout=timeout)
        if self._worker.is_alive():
            with self._lock:
                victims = list(self._queue) + list(self._inflight)
                self._queue.clear()
            err = SchedulerClosed(
                "scheduler closed while its runner was wedged; this "
                "request will never run")
            for r in victims:
                r.settle(error=err)
            raise TimeoutError(
                f"scheduler worker did not exit within {timeout}s (the "
                f"runner call is still blocked); {len(victims)} pending "
                f"future(s) were failed with SchedulerClosed")

    # ------------------------------------------------------------ worker
    @staticmethod
    def _shape_key(req):
        return tuple((a.shape[1:], str(a.dtype)) for a in req.inputs)

    def _take_group(self):
        """Pop a shape-compatible group (<= max_batch rows) or None."""
        if not self._queue:
            return None
        key = self._shape_key(self._queue[0])
        group, rows, rest = [], 0, []
        for req in self._queue:
            fits = rows + req.n <= self.max_batch or not group
            # `not group`: a single request larger than max_batch still
            # runs (alone) — it must never starve in the queue
            if self._shape_key(req) == key and fits:
                group.append(req)
                rows += req.n
            else:
                rest.append(req)
        self._queue = rest
        return group

    def _expire_locked(self):
        """Fail queued requests whose deadline passed BEFORE any runner
        time is spent on them (called with the lock held)."""
        if not any(r.deadline is not None for r in self._queue):
            return
        now = self._clock.now()
        keep = []
        for r in self._queue:
            if r.deadline is not None and now >= r.deadline:
                r.settle(error=DeadlineExceeded(
                    "request expired in the scheduler queue"))
            else:
                keep.append(r)
        self._queue = keep

    def _loop(self):
        while True:
            with self._lock:
                while not self._queue and not self._closed:
                    self._lock.wait()
                if not self._queue and self._closed:
                    return
                first_seen = time.monotonic()
                # linger for more requests while the batch is open
                while (len(self._queue) > 0
                       and sum(r.n for r in self._queue) < self.max_batch
                       and not self._closed
                       and time.monotonic() - first_seen < self.max_delay):
                    self._lock.wait(timeout=self.max_delay / 4)
                self._expire_locked()
                group = self._take_group()
                self._inflight = group or []
            if not group:
                continue
            try:
                if self._m:
                    t_launch = self._clock.now()
                    for r in group:
                        self._m["wait_s"].observe(t_launch - r.t_submit)
                    self._m["rows"].observe(sum(r.n for r in group))
                stacked = [np.concatenate([r.inputs[i] for r in group], 0)
                           for i in range(len(group[0].inputs))]
                outs = self._run(stacked)
                self.batches_run += 1
                if self._m:
                    self._m["batches"].inc()
                    self._m["batch_s"].observe(
                        self._clock.now() - t_launch)
                off = 0
                for r in group:
                    # settle() resolves the race with a close() that
                    # already failed this future
                    r.settle([np.asarray(o)[off:off + r.n] for o in outs])
                    off += r.n
            except Exception as e:              # propagate to every waiter
                if self._m:
                    self._m["failures"].inc()
                for r in group:
                    r.settle(error=e)
            finally:
                with self._lock:
                    self._inflight = []


def serve_metrics(target, host="127.0.0.1", port=0):
    """Expose a serving stack's telemetry over HTTP: ``/metrics``
    (Prometheus text), ``/stats`` (JSON snapshot + process stats), and
    — when ``target`` reports health — ``/healthz`` (200 while serving,
    503 otherwise: the load-balancer readiness contract).

    ``target`` is a ``ContinuousBatchingServer`` (uses its attached
    ``telemetry``), a ``router.ReplicaRouter`` (its ``/healthz``
    AGGREGATES the fleet: 200 iff >= 1 replica is serving, and
    ``/stats`` carries per-replica health/queue/stats), a
    ``ServerTelemetry``, or a bare ``MetricRegistry``.
    Returns a started ``telemetry.MetricsServer`` (``.url``, ``.port``,
    ``.close()``). ``port=0`` binds an ephemeral port.

    Debug surfaces (ISSUE 10): server/router targets serve their
    captured bundles on ``/debug/postmortem`` (the router aggregates
    its own plus every replica's; an empty list without a
    ``FlightRecorder``) and per-request journey timelines on
    ``/debug/journey/<rid>`` (router-minted at the front door,
    server-minted on a standalone server constructed with
    ``journeys=``; 404 for unknown rids — every rid, without a
    ``JourneyRecorder``).

    Fleet surfaces (ISSUE 11): router targets serve ONE merged
    Prometheus page across every replica's registry on ``/fleet``
    (``router.fleet_metrics()``) and — with an ``SLOEngine`` attached
    (``ReplicaRouter(slos=...)``) — the burn-rate report on ``/slo``,
    whose worst state also rides the ``/healthz`` body as an ``"slo"``
    detail (the 200/503 readiness verdict is unchanged). A server
    constructed with a ``GoodputLedger`` exposes its token-attribution
    summary under ``/stats["goodput"]``.
    """
    from ..telemetry.exposition import MetricsServer

    extra = None
    tele = getattr(target, "telemetry", target)
    if tele is None:
        raise ValueError(
            "server has no telemetry attached — construct it with "
            "telemetry=True (or a ServerTelemetry) to expose metrics")
    registry = getattr(tele, "registry", tele)
    if hasattr(target, "replicas"):       # ReplicaRouter front door

        def extra():
            stats = dict(target.stats)
            stats["replicas"] = [
                {"health": rep.health,
                 "queue_depth": rep.queue_depth(),
                 "in_flight": rep.in_flight(),
                 "stats": dict(rep.stats),
                 # goodput ratio + MFU when the replica wires a
                 # ledger/cost catalog ({} otherwise); remote replicas
                 # answer from their last heartbeat digest — no
                 # registry pull
                 "util": (rep.utilization()
                          if callable(getattr(rep, "utilization",
                                              None)) else {})}
                for rep in target.replicas]
            return stats
    elif hasattr(target, "stats"):        # ContinuousBatchingServer
        kv = getattr(target, "_kv", None)

        def extra():
            stats = dict(target.stats)
            if kv is not None:
                stats["kv_pool"] = kv.telemetry_stats()
                stats["prefix_cache"] = target._prefix.stats()
                tier = getattr(target, "_host", None)
                if tier is not None:
                    stats["host_tier"] = tier.stats()
            g = target.goodput() if callable(
                getattr(target, "goodput", None)) else None
            if g is not None:
                stats["goodput"] = g
            c = target.device_costs() if callable(
                getattr(target, "device_costs", None)) else None
            if c is not None:
                stats["costs"] = c
            return stats
    health = None
    if hasattr(target, "health"):

        def health():
            return target.health
    journey = None
    if callable(getattr(target, "journey", None)):
        def journey(rid_s, _fn=target.journey):
            try:
                rid = int(rid_s)
            except (TypeError, ValueError):
                return None
            return _fn(rid)
    postmortem = getattr(target, "postmortems", None)
    if not callable(postmortem):
        postmortem = None
    fleet = getattr(target, "fleet_metrics", None)
    if not callable(fleet):
        fleet = None
    slo = slo_states = None
    if getattr(target, "slo_engine", None) is not None \
            and getattr(target.slo_engine, "enabled", False):
        slo = target.slo_report
        # /healthz reads the CACHED states (one dict copy per probe);
        # /slo scrapes are the only evaluation driver
        slo_states = target.slo_engine.states
    return MetricsServer(registry, host=host, port=port,
                         extra_stats=extra, health=health,
                         journey=journey, postmortem=postmortem,
                         fleet=fleet, slo=slo,
                         slo_states=slo_states).start()
