"""Host-RAM KV tier: the spill store under the prefix cache (ISSUE 17).

The prefix cache used to live and die inside one chip's HBM — a cold
prefix page was EVICTED, so per-user conversation history (the
dominant millions-of-users workload) could not stay resident between
turns. ``HostTier`` is the second LRU tier that fixes that: eviction
becomes spill-to-host instead of drop. The reference framework's L0
memory layer is built around exactly this device-pool-over-host-
allocation split (PAPER.md; ``_compat.host_memory_kind`` probes the
JAX backend for the pinned-host memory kind this models).

Division of labour — the tier is deliberately DUMB:

- ``HostTier`` stores page PAYLOADS: per-page K/V numpy buffers
  (gathered per-shard off the pool by the server, concatenated on the
  kv-head dim), each sha256-checksummed like ``reliability/ckpt.py``
  payloads, under a ``budget_bytes`` cap. It owns the byte accounting
  and the ``tier.spill`` / ``tier.restore`` fault points.
- ``PrefixCache`` keeps owning the TREE: which nodes are ``hot``
  (pool page) vs ``host`` (spilled entry), the cross-tier LRU order
  (node ``last_used``/``seq`` — one clock for both tiers), spill-on-
  evict, budget-driven host eviction (the bottom of the hierarchy,
  where pages are finally forgotten), and sketch membership — spilled
  runs KEEP their fingerprints, so a router routes a returning
  session to the replica holding its history in EITHER tier.
- The server does the DEVICE work: per-shard page gathers at spill
  (``jax.device_get`` on addressable shards — never a full-pool
  replication bounce), per-shard scatters at restore
  (``jax.device_put`` against the pool's sharding), and re-entry
  through the normal ``admit_slot``/refcount path, so a restored run
  is bit-exact with a never-evicted one.

Integrity contract: ``get()`` re-hashes the payload and returns None
on mismatch — a corrupted host buffer is a cache MISS plus a counter
(``kv_host_restore_corrupt_total``), never a serving failure; the
caller drops the unrecoverable node.

A DISABLED tier (``enabled=False``) is treated by the server exactly
like None — zero locks, zero clock reads, structurally free, the same
contract as the recorder/ledger/cost-catalog subsystems. The tier
itself takes no locks at all: it is mutated exclusively under the
server lock, like the radix tree above it.
"""
import hashlib

import numpy as np

from .._compat import host_memory_kind
from ..reliability.faults import TIER_RESTORE, TIER_SPILL

__all__ = ["HostTier", "HostEntry"]


def _sha256(arrays):
    h = hashlib.sha256()
    for a in arrays:
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


class HostEntry:
    """One spilled page: ``payload`` is the page's K and V rows as
    host numpy arrays (full kv-head width — shard gathers are
    concatenated before the store), ``sha256`` the digest verified on
    every read."""

    __slots__ = ("payload", "nbytes", "sha256")

    def __init__(self, payload, nbytes, sha256):
        self.payload = payload
        self.nbytes = nbytes
        self.sha256 = sha256


class HostTier:
    """Checksummed host-RAM byte store for spilled KV pages.

    >>> tier = HostTier(budget_bytes=64 << 20)
    >>> srv = ContinuousBatchingServer(model, cache_backend="paged",
    ...                                host_tier=tier)

    ``budget_bytes=None`` means unbounded (the prefix cache never asks
    it to shrink). The LRU across both tiers lives in the radix tree's
    node clocks; the tier only answers ``over_budget()``.
    """

    def __init__(self, budget_bytes=None, enabled=True,
                 fault_injector=None):
        self.enabled = bool(enabled)
        self.budget_bytes = None if budget_bytes is None \
            else int(budget_bytes)
        if self.budget_bytes is not None and self.budget_bytes < 0:
            raise ValueError("budget_bytes must be >= 0 or None")
        self._faults = fault_injector
        self.bytes_used = 0
        self.entries = 0
        # the memory kind the backend would place pinned host buffers
        # in (probe only: payloads are plain numpy today — promoting
        # them to pinned-host jax buffers with async DMA is the
        # remaining half of ROADMAP item 5)
        self.memory_kind = host_memory_kind()
        # cumulative churn (the server mirrors these into telemetry
        # and the cost ledger after each commit)
        self.spilled_pages_total = 0    # put() commits
        self.restored_pages_total = 0   # get() hits handed back
        self.restore_corrupt_total = 0  # checksum mismatches (= misses)
        self.evicted_pages_total = 0    # entries dropped for real

    # ----------------------------------------------------------- store
    def put(self, arrays, **ctx):
        """Adopt one page's payload (a sequence of numpy arrays — K
        rows then V rows). Raises (``tier.spill`` fault) strictly
        BEFORE any state changes: on failure the caller still owns the
        device page and simply drops it. Returns the ``HostEntry``."""
        if self._faults is not None:
            self._faults.check(TIER_SPILL, **ctx)
        payload = tuple(np.ascontiguousarray(a) for a in arrays)
        nbytes = sum(a.nbytes for a in payload)
        entry = HostEntry(payload, nbytes, _sha256(payload))
        self.bytes_used += nbytes
        self.entries += 1
        self.spilled_pages_total += 1
        return entry

    def get(self, entry, **ctx):
        """The entry's payload, checksum-verified — or None when the
        buffer no longer hashes to its digest (the caller treats that
        as a MISS and forgets the node; ``restore_corrupt_total``
        counts it). Raises (``tier.restore`` fault) strictly BEFORE
        the read — an injected restore failure is a transient miss,
        never a serving failure, and changes no state."""
        if self._faults is not None:
            self._faults.check(TIER_RESTORE, **ctx)
        if _sha256(entry.payload) != entry.sha256:
            self.restore_corrupt_total += 1
            return None
        self.restored_pages_total += 1
        return entry.payload

    def discard(self, entry, evicted=False):
        """Drop an entry's bytes: a restore promoted it back to the
        pool, its node left the tree (corrupt / subtree drop), or —
        ``evicted=True`` — the cross-tier LRU pushed it off the bottom
        of the hierarchy (the one place a page is finally forgotten)."""
        self.bytes_used -= entry.nbytes
        self.entries -= 1
        if evicted:
            self.evicted_pages_total += 1

    def over_budget(self):
        return self.budget_bytes is not None \
            and self.bytes_used > self.budget_bytes

    # ------------------------------------------------------ accounting
    def stats(self):
        """Point-in-time store state + cumulative churn, plain data —
        the ``occupancy()`` / postmortem ``host_tier`` section."""
        return {"entries": self.entries,
                "bytes_used": self.bytes_used,
                "budget_bytes": self.budget_bytes,
                "memory_kind": self.memory_kind,
                "spilled_pages_total": self.spilled_pages_total,
                "restored_pages_total": self.restored_pages_total,
                "restore_corrupt_total": self.restore_corrupt_total,
                "evicted_pages_total": self.evicted_pages_total}
