"""paddle.geometric parity: graph message passing + segment ops + sampling.

Reference: python/paddle/geometric/ (math.py segment ops, message_passing/
send_u_recv & friends, reindex.py, sampling/neighbors.py; CUDA kernels
paddle/phi/kernels/gpu/graph_send_recv_kernel.cu etc.).

TPU-native design: everything is jax.ops.segment_* — XLA lowers these to
sorted-scatter which the TPU vectorises; no hand-written gather/scatter
kernels needed. `sample_neighbors`/`reindex_graph` are host-side graph prep
(numpy), matching their role as dataloader-adjacent utilities.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, unwrap, wrap

__all__ = [
    "segment_sum", "segment_mean", "segment_min", "segment_max",
    "send_u_recv", "send_ue_recv", "send_uv", "reindex_graph",
    "reindex_heter_graph", "sample_neighbors",
]


def _arr(x):
    return unwrap(x) if isinstance(x, Tensor) else jnp.asarray(x)


def _num_segments(segment_ids, out_size=None):
    if out_size is not None:
        return int(out_size)
    ids = np.asarray(segment_ids)
    return int(ids.max()) + 1 if ids.size else 0


# -- segment ops (reference python/paddle/geometric/math.py) -------------

def segment_sum(data, segment_ids, name=None):
    d, ids = _arr(data), _arr(segment_ids).astype(jnp.int32)
    n = _num_segments(segment_ids)
    return wrap(jax.ops.segment_sum(d, ids, num_segments=n),
                stop_gradient=False)


def segment_mean(data, segment_ids, name=None):
    d, ids = _arr(data), _arr(segment_ids).astype(jnp.int32)
    n = _num_segments(segment_ids)
    return wrap(_reduce(d, ids, n, "mean"), stop_gradient=False)


def _zero_empty(out, ids, n):
    # empty segments: reference returns 0; jax returns the reduction
    # identity (+/-inf for floats, INT_MIN/MAX for ints)
    cnt = jax.ops.segment_sum(jnp.ones(ids.shape, jnp.int32), ids,
                              num_segments=n)
    mask = cnt.reshape((-1,) + (1,) * (out.ndim - 1)) > 0
    return jnp.where(mask, out, jnp.zeros_like(out))


def segment_min(data, segment_ids, name=None):
    d, ids = _arr(data), _arr(segment_ids).astype(jnp.int32)
    n = _num_segments(segment_ids)
    out = jax.ops.segment_min(d, ids, num_segments=n)
    return wrap(_zero_empty(out, ids, n), stop_gradient=False)


def segment_max(data, segment_ids, name=None):
    d, ids = _arr(data), _arr(segment_ids).astype(jnp.int32)
    n = _num_segments(segment_ids)
    out = jax.ops.segment_max(d, ids, num_segments=n)
    return wrap(_zero_empty(out, ids, n), stop_gradient=False)


# -- message passing (reference message_passing/send_recv.py) ------------

_REDUCERS = {
    "sum": jax.ops.segment_sum,
    "mean": None,  # handled specially
    "min": jax.ops.segment_min,
    "max": jax.ops.segment_max,
}


def _reduce(msg, dst, n, reduce_op):
    if reduce_op == "mean":
        s = jax.ops.segment_sum(msg, dst, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones((msg.shape[0],), msg.dtype), dst,
                                  num_segments=n)
        return s / jnp.maximum(cnt, 1).reshape(
            (-1,) + (1,) * (msg.ndim - 1))
    out = _REDUCERS[reduce_op](msg, dst, num_segments=n)
    if reduce_op in ("min", "max"):
        out = _zero_empty(out, dst, n)
    return out


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src] and segment-reduce onto dst (graph_send_recv kernel)."""
    xd = _arr(x)
    src = _arr(src_index).astype(jnp.int32)
    dst = _arr(dst_index).astype(jnp.int32)
    n = out_size if out_size is not None else xd.shape[0]
    return wrap(_reduce(xd[src], dst, int(n), reduce_op),
                stop_gradient=False)


_MSG_OPS = {
    "add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
    "div": jnp.divide,
}


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """message = x[src] (op) y[edge]; then segment-reduce onto dst."""
    xd, yd = _arr(x), _arr(y)
    src = _arr(src_index).astype(jnp.int32)
    dst = _arr(dst_index).astype(jnp.int32)
    msg = _MSG_OPS[message_op](xd[src], yd)
    n = out_size if out_size is not None else xd.shape[0]
    return wrap(_reduce(msg, dst, int(n), reduce_op), stop_gradient=False)


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message x[src] (op) y[dst] (graph_send_uv kernel)."""
    xd, yd = _arr(x), _arr(y)
    src = _arr(src_index).astype(jnp.int32)
    dst = _arr(dst_index).astype(jnp.int32)
    return wrap(_MSG_OPS[message_op](xd[src], yd[dst]), stop_gradient=False)


# -- graph prep, host-side (reference reindex.py / sampling) -------------

def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Compact global node ids to local ids (reference
    python/paddle/geometric/reindex.py reindex_graph)."""
    xn = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    nb = np.asarray(neighbors.numpy() if isinstance(neighbors, Tensor)
                    else neighbors)
    cnt = np.asarray(count.numpy() if isinstance(count, Tensor) else count)
    uniq, first_idx = np.unique(np.concatenate([xn, nb]), return_index=True)
    # preserve first-appearance order (x nodes first), like the reference
    order = np.argsort(first_idx)
    nodes = uniq[order]
    remap = {int(g): i for i, g in enumerate(nodes)}
    reindex_src = np.asarray([remap[int(g)] for g in nb], np.int64)
    reindex_dst = np.repeat(np.arange(len(xn), dtype=np.int64), cnt)
    return (wrap(jnp.asarray(reindex_src)), wrap(jnp.asarray(reindex_dst)),
            wrap(jnp.asarray(nodes)))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Heterogeneous variant: per-edge-type neighbor/count lists share one
    node remap; dst is rebuilt per type (each count_i has len(x) entries)."""
    xn = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    nbs = [np.asarray(n.numpy() if isinstance(n, Tensor) else n)
           for n in neighbors]
    cnts = [np.asarray(c.numpy() if isinstance(c, Tensor) else c)
            for c in count]
    all_nb = np.concatenate(nbs) if nbs else np.empty(0, np.int64)
    # one flat count vector reusing reindex_graph's remap/src logic: the
    # concatenated neighbors belong to num_types repetitions of x
    flat_counts = np.concatenate(cnts) if cnts else np.empty(0, np.int64)
    rep_x = np.tile(np.arange(len(xn)), len(nbs))
    src, _, nodes = reindex_graph(xn, all_nb,
                                  np.zeros(len(xn), np.int64))
    reindex_dst = np.repeat(rep_x, flat_counts)
    return (src, wrap(jnp.asarray(reindex_dst.astype(np.int64))), nodes)


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """Uniform neighbor sampling on a CSC graph (host-side; reference
    python/paddle/geometric/sampling/neighbors.py)."""
    r = np.asarray(row.numpy() if isinstance(row, Tensor) else row)
    cp = np.asarray(colptr.numpy() if isinstance(colptr, Tensor) else colptr)
    nodes = np.asarray(input_nodes.numpy()
                       if isinstance(input_nodes, Tensor) else input_nodes)
    e = np.asarray(eids.numpy() if isinstance(eids, Tensor) else eids) \
        if eids is not None else None
    rng = np.random.RandomState()
    out_nb, out_cnt, out_eids = [], [], []
    for v in nodes:
        beg, end = int(cp[v]), int(cp[v + 1])
        deg = end - beg
        if sample_size < 0 or deg <= sample_size:
            pick = np.arange(beg, end)
        else:
            pick = beg + rng.choice(deg, size=sample_size, replace=False)
        out_nb.append(r[pick])
        out_cnt.append(len(pick))
        if return_eids and e is not None:
            out_eids.append(e[pick])
    neighbors = np.concatenate(out_nb) if out_nb else np.empty(0, r.dtype)
    counts = np.asarray(out_cnt, np.int32)
    if return_eids:
        ee = np.concatenate(out_eids) if out_eids else np.empty(0)
        return wrap(jnp.asarray(neighbors)), wrap(jnp.asarray(counts)), \
            wrap(jnp.asarray(ee))
    return wrap(jnp.asarray(neighbors)), wrap(jnp.asarray(counts))
