"""paddle_tpu.jit — the compiled-execution bridge.

Reference analogue: python/paddle/jit (dy2static AST transpiler +
ProgramTranslator, api.py:222 to_static). TPU-native design: there is no AST
surgery — a Layer built with paddle_tpu ops is already JAX-traceable, so
`to_static` simply wraps it as a pure function of (params, inputs) and
`jax.jit`s it. `functional_call` is the core primitive: run an eager Layer
with substituted parameter values under a trace.
"""
from __future__ import annotations

import functools

import jax

from ..core import random as rnd
from ..core.tensor import Tensor, param_substitution, unwrap
from ..core.tape import no_grad

__all__ = ["functional_call", "to_static", "TranslatedLayer", "grad_and_loss",
           "train_step_fn", "not_to_static", "save", "load"]


def _unwrap_tree(tree):
    return jax.tree_util.tree_map(
        lambda x: unwrap(x) if isinstance(x, Tensor) else x, tree,
        is_leaf=lambda x: isinstance(x, Tensor))


def _wrap_tree(tree):
    from ..core.tensor import wrap
    return jax.tree_util.tree_map(wrap, tree)


def functional_call(layer, params, *args, rng=None, buffers=None, **kwargs):
    """Run ``layer(*args)`` with parameter values taken from ``params``.

    params: dict name -> array (as from ``layer.raw_params()``). Buffers may
    be substituted the same way. Returns raw arrays (pytree). Differentiable
    w.r.t. params via jax.grad around this call.
    """
    named = dict(layer.named_parameters())
    subst = {}
    for name, value in params.items():
        subst[id(named[name])] = value
    if buffers:
        named_buf = dict(layer.named_buffers())
        for name, value in buffers.items():
            subst[id(named_buf[name])] = value
    args = jax.tree_util.tree_map(
        lambda x: x, args, is_leaf=lambda x: isinstance(x, Tensor))

    ctx = rnd.rng_scope(rng) if rng is not None else None
    with no_grad(), param_substitution(subst):
        if ctx is not None:
            with ctx:
                out = layer(*args, **kwargs)
        else:
            out = layer(*args, **kwargs)
    return _unwrap_tree(out)


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """paddle.jit.to_static parity: returns a compiled callable.

    For a Layer: returns a TranslatedLayer whose __call__ is jitted over
    (params, buffers, inputs). For a function: jax.jit with Tensor wrap/unwrap.
    """
    def decorate(fn):
        from ..nn.layer import Layer
        if isinstance(fn, Layer):
            return TranslatedLayer(fn)
        # AST pass first: tensor-dependent if/while/for become lax control
        # flow (reference ast_transformer pipeline), then jit
        from .dy2static import convert_to_static
        static_fn = convert_to_static(fn)

        @functools.wraps(fn)
        def wrapper(*args, **kw):
            if not _to_static_enabled[0]:
                # jit.enable_to_static(False): run the original eagerly
                return fn(*args, **kw)
            vals = _unwrap_tree(args)
            out = _jitted(static_fn)(*vals, **kw)
            return _wrap_tree(out)

        return wrapper

    if function is not None:
        return decorate(function)
    return decorate


@functools.lru_cache(maxsize=256)
def _jitted(fn):
    def pure(*vals, **kw):
        with no_grad():
            wrapped = _wrap_tree(vals)
            out = fn(*wrapped, **kw)
        return _unwrap_tree(out)

    return jax.jit(pure)


def not_to_static(fn):
    fn._not_to_static = True
    return fn


class TranslatedLayer:
    """Jit-compiled facade over a Layer (reference: StaticFunction/
    PartialProgramLayer, python/paddle/jit/dy2static/program_translator.py)."""

    def __init__(self, layer):
        self._layer = layer

        def pure(params, buffers, rng, *vals, training=True):
            layer.training = training
            return functional_call(layer, params, *vals, rng=rng,
                                   buffers=buffers)

        self._pure = jax.jit(pure, static_argnames=("training",))

    @property
    def layer(self):
        return self._layer

    def __call__(self, *args, **kwargs):
        params = self._layer.raw_params()
        buffers = {n: unwrap(b) for n, b in self._layer.named_buffers()}
        vals = _unwrap_tree(args)
        key = rnd.next_key()
        out = self._pure(params, buffers, key, *vals,
                         training=self._layer.training)
        return _wrap_tree(out)

    def __getattr__(self, name):
        return getattr(self._layer, name)


def grad_and_loss(layer, loss_fn):
    """Build a pure (params, batch, rng) -> (loss, grads) function."""

    def compute(params, batch, rng=None):
        out = functional_call(layer, params, *batch, rng=rng)
        return loss_fn(out)

    return jax.value_and_grad(compute)


def train_step_fn(layer, loss_fn, optimizer, donate=True):
    """One jitted train step over (params, opt_state, batch, step, rng).

    This is the TPU replacement for the reference's per-op dygraph hot loop
    (SURVEY §3.1): the whole forward/backward/update traces to one XLA
    program.
    """
    _, update_fn = optimizer.functional()

    def step(params, opt_state, batch, step_i, rng=None, lr=None):
        def compute(ps):
            out = functional_call(layer, ps, *batch["inputs"], rng=rng)
            return loss_fn(out, *batch.get("labels", ()))

        loss, grads = jax.value_and_grad(compute)(params)
        if optimizer._grad_clip is not None:
            from ..nn.clip import clip_by_global_norm_tree
            grads, _ = clip_by_global_norm_tree(
                grads, optimizer._grad_clip.clip_norm)
        new_params, new_state = update_fn(grads, params, opt_state, lr=lr,
                                          step=step_i)
        return loss, new_params, new_state

    donate_argnums = (0, 1) if donate else ()
    return jax.jit(step, donate_argnums=donate_argnums)


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save parity: persist params; with input_spec also export a
    StableHLO inference archive loadable by paddle_tpu.inference."""
    from ..io.save_load import save as _save
    state = layer.state_dict() if hasattr(layer, "state_dict") else layer
    _save(state, path + ".pdparams")
    if input_spec is not None and hasattr(layer, "raw_params"):
        from ..inference.export import export_layer
        export_layer(path, layer, input_spec)


def load(path, **configs):
    """Returns a callable ExportedProgram when a StableHLO archive exists at
    ``path`` (jit.save with input_spec); otherwise the pickled state dict."""
    import os
    if os.path.exists(path + ".pdmodel"):
        from ..inference.export import load_exported
        prog, _, _ = load_exported(path)
        return prog
    from ..io.save_load import load as _load
    return _load(path + ".pdparams")


# ------------------------------------------------- config-surface parity
# (reference python/paddle/jit/api.py + dy2static logging_utils)

_ignored_modules = []
_to_static_enabled = [True]


def ignore_module(modules):
    """Reference jit.ignore_module: functions defined in the listed
    modules are never transformed by to_static (consulted in
    dy2static.convert_to_static)."""
    if not isinstance(modules, (list, tuple)):
        modules = [modules]
    _ignored_modules.extend(modules)
    return _ignored_modules


def enable_to_static(flag=True):
    """Reference jit.enable_to_static: global switch — when off,
    to_static-wrapped functions run eagerly untransformed."""
    _to_static_enabled[0] = bool(flag)


_verbosity = [0]
_code_level = [0]


def set_verbosity(level=0, also_to_stdout=False):
    _verbosity[0] = int(level)


def set_code_level(level=100, also_to_stdout=False):
    _code_level[0] = int(level)
