"""dy2static: AST transforms turning tensor-dependent Python control flow
into XLA-traceable lax primitives.

Reference: python/paddle/jit/dy2static/ — ast_transformer.py (15
transformers), convert_operators.py (convert_ifelse/convert_while_loop/
convert_logical_and...), program_translator.py StaticFunction cache.

TPU-native: instead of rewriting to a ProgramDesc, the rewritten function
stays a JAX-traceable Python function — `if` on a traced scalar becomes
`lax.cond`, `while` becomes `lax.while_loop`, `for i in range(traced_n)`
becomes `lax.fori_loop`, and `and/or/not` on tensors become logical ops.
When the predicate is a concrete Python value the original Python control
flow runs unchanged, so one transformed function serves both eager and
traced execution (the reference's dual-mode contract).

Supported rewrite subset (same shape as the reference's core transformers):
variables mutated in a branch/loop must already be bound before it, and
branches must produce matching pytree structures — both are the standard
lax.cond/while_loop contracts; violations raise with a clear message.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["convert_to_static", "Dy2StaticError", "convert_ifelse",
           "convert_while_loop", "convert_for_range", "convert_logical_and",
           "convert_logical_or", "convert_logical_not", "convert_bool",
           "convert_ifexp", "convert_assert", "convert_print", "UNDEFINED"]


class Dy2StaticError(RuntimeError):
    pass


class _Undefined:
    """Placeholder for a name first bound inside a control-flow branch
    (reference mechanism: dy2static UndefinedVar,
    python/paddle/jit/dy2static/utils.py). Seeded before the rewritten
    `if` so referencing the name as a lax.cond operand is legal; using the
    value itself raises a clear error instead of UnboundLocalError."""

    def _err(self, *a, **k):
        raise Dy2StaticError(
            "variable was only assigned along one control-flow branch and "
            "is used before being defined on the taken path")

    __bool__ = __add__ = __radd__ = __sub__ = __rsub__ = _err
    __mul__ = __rmul__ = __truediv__ = __rtruediv__ = _err
    __floordiv__ = __rfloordiv__ = __mod__ = __rmod__ = _err
    __pow__ = __rpow__ = __matmul__ = __rmatmul__ = _err
    __neg__ = __pos__ = __abs__ = __invert__ = _err
    __eq__ = __ne__ = __lt__ = __le__ = __gt__ = __ge__ = _err
    __call__ = __getitem__ = __setitem__ = __iter__ = __len__ = _err
    __int__ = __float__ = __index__ = __complex__ = _err
    __array__ = __contains__ = _err
    __hash__ = object.__hash__  # defining __eq__ would otherwise unset it

    def __getattr__(self, name):
        # dunder probes (copy/pickle/inspect protocols) must fall through
        # as plain AttributeError; any real attribute use is an error
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        self._err()

    def __repr__(self):
        return "<dy2static UNDEFINED>"


UNDEFINED = _Undefined()

# zero-leaf pytree: UNDEFINED may ride through lax.cond operands/results
# without being treated as an array
try:
    jax.tree_util.register_pytree_node(
        _Undefined, lambda u: ((), None), lambda aux, ch: UNDEFINED)
except ValueError:
    pass  # module re-import: already registered


def _seed_stmts(names):
    """`try: n\nexcept NameError: n = UNDEFINED` for each name, so names
    first bound inside the rewritten block exist before the runtime call."""
    return [ast.Try(
        body=[ast.Expr(value=_name(n))],
        handlers=[ast.ExceptHandler(
            type=_name("NameError"), name=None,
            body=[ast.Assign(targets=[_name(n, ast.Store)],
                             value=_jst_attr("UNDEFINED"))])],
        orelse=[], finalbody=[]) for n in names]


# ---------------------------------------------------------------- runtime

def _raw(x):
    from ..core.tensor import Tensor, unwrap
    return unwrap(x) if isinstance(x, Tensor) else x


def _is_traced(x):
    x = _raw(x)
    return isinstance(x, jax.core.Tracer)


def _pred(x):
    """Predicate -> traced bool scalar or Python bool. Concrete values
    (incl. np.bool_/0-d arrays, which are NOT Python bool) always become
    a real bool so the eager fast path is taken."""
    r = _raw(x)
    if getattr(r, "ndim", 0) != 0 and getattr(r, "size", 1) != 1:
        raise Dy2StaticError(
            "control-flow predicate must be a scalar (got shape "
            f"{getattr(r, 'shape', None)})")
    if _is_traced(r):
        return r.reshape(()).astype(bool)
    return bool(r)


def convert_ifelse(pred, true_fn, false_fn, args):
    """reference convert_operators.py convert_ifelse."""
    p = _pred(pred)
    if isinstance(p, bool):
        return true_fn(*args) if p else false_fn(*args)
    from ..core.tensor import Tensor, unwrap

    def strip(vals):
        return jax.tree_util.tree_map(
            lambda v: unwrap(v) if isinstance(v, Tensor) else v, vals,
            is_leaf=lambda v: isinstance(v, Tensor))

    args = strip(tuple(args))  # lax.cond operands must be raw arrays
    try:
        return lax.cond(p, lambda a: strip(true_fn(*a)),
                        lambda a: strip(false_fn(*a)), args)
    except TypeError as e:
        raise Dy2StaticError(
            f"if/else branches returned mismatched structures under "
            f"tracing: {e}") from None


def convert_while_loop(cond_fn, body_fn, carry):
    p = _pred(cond_fn(*carry))
    if isinstance(p, bool):  # concrete: plain Python loop
        while _pred(cond_fn(*carry)):
            carry = body_fn(*carry)
        return carry

    def c(state):
        return _pred(cond_fn(*state))

    def b(state):
        return tuple(body_fn(*state))

    return tuple(lax.while_loop(c, b, tuple(carry)))


def convert_for_range(n, body_fn, carry):
    """for i in range(n) with possibly-traced n -> fori_loop."""
    if not _is_traced(n):
        for i in range(int(_raw(n))):
            carry = body_fn(i, *carry)
        return carry

    def b(i, state):
        return tuple(body_fn(i, *state))

    return tuple(lax.fori_loop(0, _raw(n), b, tuple(carry)))


def convert_logical_and(lhs_fn, rhs_fn):
    l = lhs_fn()
    if not _is_traced(l):
        return rhs_fn() if l else l
    return jnp.logical_and(_raw(l), _raw(rhs_fn()))


def convert_logical_or(lhs_fn, rhs_fn):
    l = lhs_fn()
    if not _is_traced(l):
        return l if l else rhs_fn()
    return jnp.logical_or(_raw(l), _raw(rhs_fn()))


def convert_logical_not(x):
    if not _is_traced(x):
        return not x
    return jnp.logical_not(_raw(x))


def convert_bool(x):
    """`if x:` predicate evaluation hook."""
    return _pred(x)


def convert_ifexp(pred, true_fn, false_fn):
    """Ternary `a if cond else b` (reference convert_operators.py
    convert_ifelse expression form). Routed through convert_ifelse so
    traced predicates get lax.cond with full pytree outputs (tuples etc.)
    instead of a structure-mangling jnp.where."""
    return convert_ifelse(pred, lambda: true_fn(), lambda: false_fn(), ())


def convert_assert(pred, message=None):
    """`assert` statement (reference convert_operators.py convert_assert
    -> Assert op). Eager: real assert. Traced: cannot branch on data —
    matches the reference's behavior of deferring to runtime checks; use
    paddle_tpu.debugging.enable_check_nan_inf for traced validation.

    ``message`` may be a zero-arg callable (the transformer wraps the msg
    expression in a lambda so it is only evaluated on failure, matching
    Python's lazy assert-message semantics)."""
    p = _pred(pred)
    if isinstance(p, bool):
        if not p:
            if callable(message):
                message = message()
            raise AssertionError(message if message is not None else "")
    return None


def convert_print(*args, **kwargs):
    """`print` (reference PrintTransformer -> Print op). Traced tensors
    print at RUN time via jax.debug.print; non-array args (labels etc.)
    fold into the format string since they aren't valid JAX types."""
    if any(_is_traced(a) for a in args):
        sep = kwargs.get("sep", " ")
        parts, arrays = [], []
        for a in args:
            r = _raw(a)
            if isinstance(r, (jax.Array, jax.core.Tracer)):
                parts.append("{}")
                arrays.append(r)
            else:
                parts.append(str(a).replace("{", "{{").replace("}", "}}"))
        jax.debug.print(sep.join(parts), *arrays)
        return None
    return print(*args, **kwargs)


# --------------------------------------------------------------- analysis

class _AssignedNames(ast.NodeVisitor):
    """Names bound by assignment/augassign/for-target inside a block."""

    def __init__(self):
        self.names = []

    def _add(self, t):
        if isinstance(t, ast.Name):
            if t.id not in self.names:
                self.names.append(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._add(e)

    def visit_Assign(self, node):
        for t in node.targets:
            self._add(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._add(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        self._add(node.target)
        self.generic_visit(node)

    def visit_For(self, node):
        self._add(node.target)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        pass  # nested defs bind their own scope


def _assigned(stmts):
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


def _load_names(node):
    return sorted({n.id for n in ast.walk(node)
                   if isinstance(n, ast.Name)
                   and isinstance(n.ctx, ast.Load)})


def _has_disallowed(stmts):
    """Return/break/continue/yield in THIS block's scope (nested function
    defs — including our own generated branch functions — have their own
    scope and must not count)."""
    def scan(node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return None
        if isinstance(node, (ast.Return, ast.Break, ast.Continue,
                             ast.Yield, ast.YieldFrom)):
            return type(node).__name__
        for child in ast.iter_child_nodes(node):
            r = scan(child)
            if r:
                return r
        return None

    for s in stmts:
        r = scan(s)
        if r:
            return r
    return None


_JST = "_paddle_tpu_jst"


def _name(n, ctx=ast.Load):
    return ast.Name(id=n, ctx=ctx())

def _jst_attr(fn):
    return ast.Attribute(value=_name(_JST), attr=fn, ctx=ast.Load())


# ------------------------------------------------------------ transformer

class _ControlFlowTransformer(ast.NodeTransformer):
    """The reference's IfElse/Loop/Logical transformers in one pass.

    ``shadowed``: names bound locally in the function being transformed
    (params + assignments) — builtin rewrites (print) skip these.
    """

    def __init__(self, shadowed=()):
        self._counter = 0
        self._shadowed = frozenset(shadowed)

    def _fresh(self, kind):
        self._counter += 1
        return f"__dy2st_{kind}_{self._counter}"

    # -- logical ops -----------------------------------------------------
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        fn = ("convert_logical_and" if isinstance(node.op, ast.And)
              else "convert_logical_or")
        out = node.values[-1]
        for v in reversed(node.values[:-1]):
            out = ast.Call(
                func=_jst_attr(fn),
                args=[ast.Lambda(args=ast.arguments(
                          posonlyargs=[], args=[], kwonlyargs=[],
                          kw_defaults=[], defaults=[]), body=v),
                      ast.Lambda(args=ast.arguments(
                          posonlyargs=[], args=[], kwonlyargs=[],
                          kw_defaults=[], defaults=[]), body=out)],
                keywords=[])
        return ast.copy_location(out, node)

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.copy_location(ast.Call(
                func=_jst_attr("convert_logical_not"),
                args=[node.operand], keywords=[]), node)
        return node

    def visit_IfExp(self, node):
        self.generic_visit(node)
        mk = lambda b: ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                               kw_defaults=[], defaults=[]), body=b)
        return ast.copy_location(ast.Call(
            func=_jst_attr("convert_ifexp"),
            args=[node.test, mk(node.body), mk(node.orelse)],
            keywords=[]), node)

    def visit_Assert(self, node):
        self.generic_visit(node)
        args = [node.test]
        if node.msg is not None:
            # lambda-wrap: Python evaluates assert messages lazily (only
            # on failure) — an eager arg would run side effects/indexing
            # on the success path too
            args.append(ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                   kw_defaults=[], defaults=[]),
                body=node.msg))
        return ast.copy_location(ast.Expr(value=ast.Call(
            func=_jst_attr("convert_assert"), args=args, keywords=[])),
            node)

    def visit_Call(self, node):
        self.generic_visit(node)
        if isinstance(node.func, ast.Name) and node.func.id == "print" \
                and "print" not in self._shadowed:
            return ast.copy_location(ast.Call(
                func=_jst_attr("convert_print"), args=node.args,
                keywords=node.keywords), node)
        return node

    # -- if/else ---------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        bad = _has_disallowed(node.body) or _has_disallowed(node.orelse)
        if bad:
            return node  # leave untransformed: works eagerly, and under
            # trace the predicate bool() raises a clear jax error
        assigned = sorted(set(_assigned(node.body))
                          | set(_assigned(node.orelse)))
        if not assigned:
            return node
        tname, fname = self._fresh("true"), self._fresh("false")
        ret = ast.Return(value=ast.Tuple(
            elts=[_name(a) for a in assigned], ctx=ast.Load()))

        def mk(fn_name, body):
            return ast.FunctionDef(
                name=fn_name,
                args=ast.arguments(
                    posonlyargs=[],
                    args=[ast.arg(arg=a) for a in assigned],
                    kwonlyargs=[], kw_defaults=[], defaults=[]),
                body=(body or [ast.Pass()]) + [ret],
                decorator_list=[], type_params=[])

        call = ast.Assign(
            targets=[ast.Tuple(elts=[_name(a, ast.Store)
                                     for a in assigned], ctx=ast.Store())],
            value=ast.Call(
                func=_jst_attr("convert_ifelse"),
                args=[node.test, _name(tname), _name(fname),
                      ast.Tuple(elts=[_name(a) for a in assigned],
                                ctx=ast.Load())],
                keywords=[]))
        out = _seed_stmts(assigned) + [mk(tname, node.body),
                                       mk(fname, node.orelse), call]
        for stmt in out:
            ast.copy_location(stmt, node)
            ast.fix_missing_locations(stmt)
        return out

    # -- while -----------------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or _has_disallowed(node.body):
            return node
        # only names REBOUND in the body become loop carries; names that
        # are merely read resolve lexically from the enclosing scope
        carry = [c for c in _assigned(node.body)
                 if not c.startswith("__dy2st")]
        if not carry:
            return node
        cname, bname = self._fresh("cond"), self._fresh("body")
        args = ast.arguments(posonlyargs=[],
                             args=[ast.arg(arg=a) for a in carry],
                             kwonlyargs=[], kw_defaults=[], defaults=[])
        cond_fn = ast.FunctionDef(
            name=cname, args=args,
            body=[ast.Return(value=node.test)], decorator_list=[], type_params=[])
        body_fn = ast.FunctionDef(
            name=bname, args=args,
            body=node.body + [ast.Return(value=ast.Tuple(
                elts=[_name(a) for a in carry], ctx=ast.Load()))],
            decorator_list=[], type_params=[])
        call = ast.Assign(
            targets=[ast.Tuple(elts=[_name(a, ast.Store) for a in carry],
                               ctx=ast.Store())],
            value=ast.Call(
                func=_jst_attr("convert_while_loop"),
                args=[_name(cname), _name(bname),
                      ast.Tuple(elts=[_name(a) for a in carry],
                                ctx=ast.Load())],
                keywords=[]))
        out = _seed_stmts(carry) + [cond_fn, body_fn, call]
        for stmt in out:
            ast.copy_location(stmt, node)
            ast.fix_missing_locations(stmt)
        return out

    # -- for i in range(...) ---------------------------------------------
    def visit_For(self, node):
        self.generic_visit(node)
        is_range = (isinstance(node.iter, ast.Call)
                    and isinstance(node.iter.func, ast.Name)
                    and node.iter.func.id == "range"
                    and len(node.iter.args) == 1
                    and isinstance(node.target, ast.Name))
        if not is_range or node.orelse or _has_disallowed(node.body):
            return node
        assigned = [a for a in _assigned(node.body)
                    if a != node.target.id and not a.startswith("__dy2st")]
        if not assigned:
            return node
        bname = self._fresh("forbody")
        body_fn = ast.FunctionDef(
            name=bname,
            args=ast.arguments(
                posonlyargs=[],
                args=[ast.arg(arg=node.target.id)]
                + [ast.arg(arg=a) for a in assigned],
                kwonlyargs=[], kw_defaults=[], defaults=[]),
            body=node.body + [ast.Return(value=ast.Tuple(
                elts=[_name(a) for a in assigned], ctx=ast.Load()))],
            decorator_list=[], type_params=[])
        call = ast.Assign(
            targets=[ast.Tuple(elts=[_name(a, ast.Store)
                                     for a in assigned], ctx=ast.Store())],
            value=ast.Call(
                func=_jst_attr("convert_for_range"),
                args=[node.iter.args[0], _name(bname),
                      ast.Tuple(elts=[_name(a) for a in assigned],
                                ctx=ast.Load())],
                keywords=[]))
        out = _seed_stmts(assigned) + [body_fn, call]
        for stmt in out:
            ast.copy_location(stmt, node)
            ast.fix_missing_locations(stmt)
        return out


# --------------------------------------------------------------- frontend
#
# Two-level cache design:
#  - `_code_cache` memoizes the EXPENSIVE part (source → AST transform →
#    compiled code object) per func.__code__; None marks untransformable.
#  - The returned function is built per closure by binding the transformed
#    code to the ORIGINAL cell objects via types.FunctionType, so free
#    variables stay live (a later `nonlocal` rebind is seen, unlike a
#    bake-values-into-globals scheme) and factory closures never share
#    state. `_fn_memo` is a small bounded LRU keyed by (code, cell ids)
#    purely to keep jax.jit caches stable across repeated to_static calls
#    on the same closure; eviction only costs a re-bind, never correctness.

_code_cache = {}   # func.__code__ -> transformed inner code object | None
_fn_memo = {}      # (code, cell-id-tuple) -> (fn, cells)  [bounded]
_FN_MEMO_MAX = 512
_MISSING = object()


def _transform_to_code(func):
    """Parse+transform func's source; return a code object whose free
    variables match the original's (so original cells can be bound)."""
    src = textwrap.dedent(inspect.getsource(func))
    tree = ast.parse(src)
    fdef = tree.body[0]
    # drop decorators: the transformed fn is called by the wrapper
    if isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        fdef.decorator_list = []
    shadowed = set()
    if isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        a = fdef.args
        shadowed = {p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)}
        if a.vararg:
            shadowed.add(a.vararg.arg)
        if a.kwarg:
            shadowed.add(a.kwarg.arg)
        shadowed |= set(_assigned(fdef.body))
    tree = _ControlFlowTransformer(shadowed=shadowed).visit(tree)
    ast.fix_missing_locations(tree)
    freevars = func.__code__.co_freevars
    if freevars:
        # wrap in an outer def whose params are the free names: compiling
        # it makes those names free in the inner code object, which we
        # then extract and later bind to the ORIGINAL cells
        outer = ast.FunctionDef(
            name="__dy2st_outer__",
            args=ast.arguments(
                posonlyargs=[],
                args=[ast.arg(arg=n) for n in freevars],
                kwonlyargs=[], kw_defaults=[], defaults=[]),
            body=[fdef, ast.Return(value=_name(fdef.name))],
            decorator_list=[], type_params=[])
        tree = ast.Module(body=[outer], type_ignores=[])
        ast.fix_missing_locations(tree)
    mod_code = compile(tree, filename=f"<dy2static {func.__name__}>",
                       mode="exec")
    # dig out the function's code object (possibly nested in the outer)
    holder = mod_code
    if freevars:
        holder = next(c for c in mod_code.co_consts
                      if isinstance(c, types.CodeType)
                      and c.co_name == "__dy2st_outer__")
    inner = next(c for c in holder.co_consts
                 if isinstance(c, types.CodeType)
                 and c.co_name == func.__name__)
    return inner


def convert_to_static(func):
    """Rewrite `func`'s control flow for tracing; returns the transformed
    function (reference: program_translator.py StaticFunction +
    ast_transformer pipeline). Falls back to the original on any source/
    parse failure (builtins, lambdas, REPL)."""
    code = getattr(func, "__code__", None)
    if code is None:
        return func
    # jit.ignore_module registry: functions defined in an ignored module
    # run untransformed (reference dy2static ignore_module semantics)
    from . import _ignored_modules
    mod_name = getattr(func, "__module__", None)
    for m in _ignored_modules:
        ignored = getattr(m, "__name__", m)
        if mod_name == ignored:
            return func
    cells = getattr(func, "__closure__", None)
    memo_key = (code, tuple(id(c) for c in cells) if cells else None)
    hit = _fn_memo.get(memo_key)
    if hit is not None:
        return hit[0]

    entry = _code_cache.get(code, _MISSING)
    if entry is _MISSING:
        try:
            entry = _transform_to_code(func)
        except (OSError, TypeError, SyntaxError, IndexError, KeyError,
                ValueError, StopIteration):
            entry = None
        _code_cache[code] = entry
    if entry is None:
        return func

    import sys
    glb = dict(func.__globals__)
    glb[_JST] = sys.modules[__name__]
    try:
        if cells:
            cellmap = dict(zip(code.co_freevars, cells))
            closure = tuple(cellmap[n] for n in entry.co_freevars)
        else:
            closure = None
        new_fn = types.FunctionType(entry, glb, func.__name__,
                                    func.__defaults__, closure)
        new_fn.__kwdefaults__ = func.__kwdefaults__
        new_fn = functools.wraps(func)(new_fn)
    except (KeyError, TypeError):
        _code_cache[code] = None
        return func
    if len(_fn_memo) >= _FN_MEMO_MAX:  # bounded: drop ~oldest half
        for k in list(_fn_memo)[:_FN_MEMO_MAX // 2]:
            del _fn_memo[k]
    _fn_memo[memo_key] = (new_fn, cells)
    return new_fn
