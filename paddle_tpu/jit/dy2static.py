"""dy2static: AST transforms turning tensor-dependent Python control flow
into XLA-traceable lax primitives.

Reference: python/paddle/jit/dy2static/ — ast_transformer.py (15
transformers), convert_operators.py (convert_ifelse/convert_while_loop/
convert_logical_and...), program_translator.py StaticFunction cache.

TPU-native: instead of rewriting to a ProgramDesc, the rewritten function
stays a JAX-traceable Python function — `if` on a traced scalar becomes
`lax.cond`, `while` becomes `lax.while_loop`, `for i in range(traced_n)`
becomes `lax.fori_loop`, and `and/or/not` on tensors become logical ops.
When the predicate is a concrete Python value the original Python control
flow runs unchanged, so one transformed function serves both eager and
traced execution (the reference's dual-mode contract).

Supported rewrite subset (same shape as the reference's core transformers):
variables mutated in a branch/loop must already be bound before it, and
branches must produce matching pytree structures — both are the standard
lax.cond/while_loop contracts; violations raise with a clear message.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["convert_to_static", "Dy2StaticError", "convert_ifelse",
           "convert_while_loop", "convert_for_range", "convert_logical_and",
           "convert_logical_or", "convert_logical_not", "convert_bool"]


class Dy2StaticError(RuntimeError):
    pass


# ---------------------------------------------------------------- runtime

def _raw(x):
    from ..core.tensor import Tensor, unwrap
    return unwrap(x) if isinstance(x, Tensor) else x


def _is_traced(x):
    x = _raw(x)
    return isinstance(x, jax.core.Tracer)


def _pred(x):
    """Predicate -> traced bool scalar or Python bool."""
    r = _raw(x)
    if isinstance(r, (jax.Array, jax.core.Tracer)):
        if getattr(r, "ndim", 0) != 0 and getattr(r, "size", 1) != 1:
            raise Dy2StaticError(
                "control-flow predicate must be a scalar (got shape "
                f"{getattr(r, 'shape', None)})")
        return r.reshape(()).astype(bool) if _is_traced(r) else \
            bool(jnp.reshape(r, ()))
    return r


def convert_ifelse(pred, true_fn, false_fn, args):
    """reference convert_operators.py convert_ifelse."""
    p = _pred(pred)
    if isinstance(p, bool):
        return true_fn(*args) if p else false_fn(*args)
    from ..core.tensor import Tensor, unwrap

    def strip(vals):
        return jax.tree_util.tree_map(
            lambda v: unwrap(v) if isinstance(v, Tensor) else v, vals,
            is_leaf=lambda v: isinstance(v, Tensor))

    args = strip(tuple(args))  # lax.cond operands must be raw arrays
    try:
        return lax.cond(p, lambda a: strip(true_fn(*a)),
                        lambda a: strip(false_fn(*a)), args)
    except TypeError as e:
        raise Dy2StaticError(
            f"if/else branches returned mismatched structures under "
            f"tracing: {e}") from None


def convert_while_loop(cond_fn, body_fn, carry):
    p = _pred(cond_fn(*carry))
    if isinstance(p, bool):  # concrete: plain Python loop
        while _pred(cond_fn(*carry)):
            carry = body_fn(*carry)
        return carry

    def c(state):
        return _pred(cond_fn(*state))

    def b(state):
        return tuple(body_fn(*state))

    return tuple(lax.while_loop(c, b, tuple(carry)))


def convert_for_range(n, body_fn, carry):
    """for i in range(n) with possibly-traced n -> fori_loop."""
    if not _is_traced(n):
        for i in range(int(_raw(n))):
            carry = body_fn(i, *carry)
        return carry

    def b(i, state):
        return tuple(body_fn(i, *state))

    return tuple(lax.fori_loop(0, _raw(n), b, tuple(carry)))


def convert_logical_and(lhs_fn, rhs_fn):
    l = lhs_fn()
    if not _is_traced(l):
        return rhs_fn() if l else l
    return jnp.logical_and(_raw(l), _raw(rhs_fn()))


def convert_logical_or(lhs_fn, rhs_fn):
    l = lhs_fn()
    if not _is_traced(l):
        return l if l else rhs_fn()
    return jnp.logical_or(_raw(l), _raw(rhs_fn()))


def convert_logical_not(x):
    if not _is_traced(x):
        return not x
    return jnp.logical_not(_raw(x))


def convert_bool(x):
    """`if x:` predicate evaluation hook."""
    return _pred(x)


# --------------------------------------------------------------- analysis

class _AssignedNames(ast.NodeVisitor):
    """Names bound by assignment/augassign/for-target inside a block."""

    def __init__(self):
        self.names = []

    def _add(self, t):
        if isinstance(t, ast.Name):
            if t.id not in self.names:
                self.names.append(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._add(e)

    def visit_Assign(self, node):
        for t in node.targets:
            self._add(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._add(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        self._add(node.target)
        self.generic_visit(node)

    def visit_For(self, node):
        self._add(node.target)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        pass  # nested defs bind their own scope


def _assigned(stmts):
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


def _load_names(node):
    return sorted({n.id for n in ast.walk(node)
                   if isinstance(n, ast.Name)
                   and isinstance(n.ctx, ast.Load)})


def _has_disallowed(stmts):
    """Return/break/continue/yield in THIS block's scope (nested function
    defs — including our own generated branch functions — have their own
    scope and must not count)."""
    def scan(node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return None
        if isinstance(node, (ast.Return, ast.Break, ast.Continue,
                             ast.Yield, ast.YieldFrom)):
            return type(node).__name__
        for child in ast.iter_child_nodes(node):
            r = scan(child)
            if r:
                return r
        return None

    for s in stmts:
        r = scan(s)
        if r:
            return r
    return None


_JST = "_paddle_tpu_jst"


def _name(n, ctx=ast.Load):
    return ast.Name(id=n, ctx=ctx())

def _jst_attr(fn):
    return ast.Attribute(value=_name(_JST), attr=fn, ctx=ast.Load())


# ------------------------------------------------------------ transformer

class _ControlFlowTransformer(ast.NodeTransformer):
    """The reference's IfElse/Loop/Logical transformers in one pass."""

    def __init__(self):
        self._counter = 0

    def _fresh(self, kind):
        self._counter += 1
        return f"__dy2st_{kind}_{self._counter}"

    # -- logical ops -----------------------------------------------------
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        fn = ("convert_logical_and" if isinstance(node.op, ast.And)
              else "convert_logical_or")
        out = node.values[-1]
        for v in reversed(node.values[:-1]):
            out = ast.Call(
                func=_jst_attr(fn),
                args=[ast.Lambda(args=ast.arguments(
                          posonlyargs=[], args=[], kwonlyargs=[],
                          kw_defaults=[], defaults=[]), body=v),
                      ast.Lambda(args=ast.arguments(
                          posonlyargs=[], args=[], kwonlyargs=[],
                          kw_defaults=[], defaults=[]), body=out)],
                keywords=[])
        return ast.copy_location(out, node)

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.copy_location(ast.Call(
                func=_jst_attr("convert_logical_not"),
                args=[node.operand], keywords=[]), node)
        return node

    # -- if/else ---------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        bad = _has_disallowed(node.body) or _has_disallowed(node.orelse)
        if bad:
            return node  # leave untransformed: works eagerly, and under
            # trace the predicate bool() raises a clear jax error
        assigned = sorted(set(_assigned(node.body))
                          | set(_assigned(node.orelse)))
        if not assigned:
            return node
        tname, fname = self._fresh("true"), self._fresh("false")
        ret = ast.Return(value=ast.Tuple(
            elts=[_name(a) for a in assigned], ctx=ast.Load()))

        def mk(fn_name, body):
            return ast.FunctionDef(
                name=fn_name,
                args=ast.arguments(
                    posonlyargs=[],
                    args=[ast.arg(arg=a) for a in assigned],
                    kwonlyargs=[], kw_defaults=[], defaults=[]),
                body=(body or [ast.Pass()]) + [ret],
                decorator_list=[], type_params=[])

        call = ast.Assign(
            targets=[ast.Tuple(elts=[_name(a, ast.Store)
                                     for a in assigned], ctx=ast.Store())],
            value=ast.Call(
                func=_jst_attr("convert_ifelse"),
                args=[node.test, _name(tname), _name(fname),
                      ast.Tuple(elts=[_name(a) for a in assigned],
                                ctx=ast.Load())],
                keywords=[]))
        out = [mk(tname, node.body), mk(fname, node.orelse), call]
        for stmt in out:
            ast.copy_location(stmt, node)
            ast.fix_missing_locations(stmt)
        return out

    # -- while -----------------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or _has_disallowed(node.body):
            return node
        # only names REBOUND in the body become loop carries; names that
        # are merely read resolve lexically from the enclosing scope
        carry = [c for c in _assigned(node.body)
                 if not c.startswith("__dy2st")]
        if not carry:
            return node
        cname, bname = self._fresh("cond"), self._fresh("body")
        args = ast.arguments(posonlyargs=[],
                             args=[ast.arg(arg=a) for a in carry],
                             kwonlyargs=[], kw_defaults=[], defaults=[])
        cond_fn = ast.FunctionDef(
            name=cname, args=args,
            body=[ast.Return(value=node.test)], decorator_list=[], type_params=[])
        body_fn = ast.FunctionDef(
            name=bname, args=args,
            body=node.body + [ast.Return(value=ast.Tuple(
                elts=[_name(a) for a in carry], ctx=ast.Load()))],
            decorator_list=[], type_params=[])
        call = ast.Assign(
            targets=[ast.Tuple(elts=[_name(a, ast.Store) for a in carry],
                               ctx=ast.Store())],
            value=ast.Call(
                func=_jst_attr("convert_while_loop"),
                args=[_name(cname), _name(bname),
                      ast.Tuple(elts=[_name(a) for a in carry],
                                ctx=ast.Load())],
                keywords=[]))
        out = [cond_fn, body_fn, call]
        for stmt in out:
            ast.copy_location(stmt, node)
            ast.fix_missing_locations(stmt)
        return out

    # -- for i in range(...) ---------------------------------------------
    def visit_For(self, node):
        self.generic_visit(node)
        is_range = (isinstance(node.iter, ast.Call)
                    and isinstance(node.iter.func, ast.Name)
                    and node.iter.func.id == "range"
                    and len(node.iter.args) == 1
                    and isinstance(node.target, ast.Name))
        if not is_range or node.orelse or _has_disallowed(node.body):
            return node
        assigned = [a for a in _assigned(node.body)
                    if a != node.target.id and not a.startswith("__dy2st")]
        if not assigned:
            return node
        bname = self._fresh("forbody")
        body_fn = ast.FunctionDef(
            name=bname,
            args=ast.arguments(
                posonlyargs=[],
                args=[ast.arg(arg=node.target.id)]
                + [ast.arg(arg=a) for a in assigned],
                kwonlyargs=[], kw_defaults=[], defaults=[]),
            body=node.body + [ast.Return(value=ast.Tuple(
                elts=[_name(a) for a in assigned], ctx=ast.Load()))],
            decorator_list=[], type_params=[])
        call = ast.Assign(
            targets=[ast.Tuple(elts=[_name(a, ast.Store)
                                     for a in assigned], ctx=ast.Store())],
            value=ast.Call(
                func=_jst_attr("convert_for_range"),
                args=[node.iter.args[0], _name(bname),
                      ast.Tuple(elts=[_name(a) for a in assigned],
                                ctx=ast.Load())],
                keywords=[]))
        out = [body_fn, call]
        for stmt in out:
            ast.copy_location(stmt, node)
            ast.fix_missing_locations(stmt)
        return out


# --------------------------------------------------------------- frontend

_cache = {}


def convert_to_static(func):
    """Rewrite `func`'s control flow for tracing; returns the transformed
    function (reference: program_translator.py StaticFunction +
    ast_transformer pipeline). Falls back to the original on any source/
    parse failure (builtins, lambdas, REPL)."""
    key = getattr(func, "__code__", None)
    if key in _cache:
        return _cache[key]
    try:
        src = textwrap.dedent(inspect.getsource(func))
        tree = ast.parse(src)
        fdef = tree.body[0]
        # drop decorators: the transformed fn is called by the wrapper
        if isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fdef.decorator_list = []
        tree = _ControlFlowTransformer().visit(tree)
        ast.fix_missing_locations(tree)
        code = compile(tree, filename=f"<dy2static {func.__name__}>",
                       mode="exec")
        import sys
        glb = dict(func.__globals__)
        glb[_JST] = sys.modules[__name__]
        # rebind the closure by executing the def in an env seeded with
        # the free variables' current values
        if func.__closure__:
            for nm, cell in zip(func.__code__.co_freevars,
                                func.__closure__):
                try:
                    glb[nm] = cell.cell_contents
                except ValueError:
                    pass
        loc = {}
        exec(code, glb, loc)
        new_fn = loc[func.__name__]
        new_fn = functools.wraps(func)(new_fn)
        _cache[key] = new_fn
        return new_fn
    except (OSError, TypeError, SyntaxError, IndexError, KeyError):
        _cache[key] = func
        return func
