"""paddle.sparse.nn.functional parity.

Reference: python/paddle/sparse/nn/functional/ (activation.py, conv.py,
pooling.py, transformer.py attention).

TPU-native notes: activations are value-maps on stored values. conv3d /
max_pool3d densify and use lax.conv_general_dilated / reduce_window — on TPU
the MXU conv path beats any gather-based sparse conv at the densities the
reference targets, and XLA fuses the re-sparsification; SubmConv3D masks the
output back to the input's sparsity pattern (submanifold semantics).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import sparse as jsparse

from ...core.tensor import Tensor, unwrap, wrap
from .. import (SparseCooTensor, SparseCsrTensor, _arr, _is_sparse)

__all__ = ["relu", "relu6", "leaky_relu", "softmax", "conv3d", "subm_conv3d",
           "max_pool3d", "attention"]


def relu(x, name=None):
    return x._map_values(jax.nn.relu) if _is_sparse(x) else \
        wrap(jax.nn.relu(_arr(x)))


def relu6(x, name=None):
    return x._map_values(jax.nn.relu6) if _is_sparse(x) else \
        wrap(jax.nn.relu6(_arr(x)))


def leaky_relu(x, negative_slope=0.01, name=None):
    fn = lambda v: jax.nn.leaky_relu(v, negative_slope)
    return x._map_values(fn) if _is_sparse(x) else wrap(fn(_arr(x)))


def softmax(x, axis=-1, name=None):
    """Row-wise softmax over stored values only (reference:
    phi sparse softmax_kernel — softmax over the nonzeros of each row)."""
    if isinstance(x, SparseCsrTensor):
        b = x._b
        if b.ndim != 2:
            d = b.todense()
            mask = d != 0
            e = jnp.where(mask, d, -jnp.inf)
            s = jax.nn.softmax(e, axis=-1)
            return SparseCsrTensor.from_dense(jnp.where(mask, s, 0))
        # per-row segment softmax on values
        nrows = b.shape[0]
        row_id = jnp.cumsum(
            jnp.zeros(b.nse, jnp.int32).at[b.indptr[1:-1]].add(1))
        vals = b.data
        rmax = jax.ops.segment_max(vals, row_id, num_segments=nrows)
        ex = jnp.exp(vals - rmax[row_id])
        rsum = jax.ops.segment_sum(ex, row_id, num_segments=nrows)
        out = ex / rsum[row_id]
        return SparseCsrTensor(jsparse.BCSR((out, b.indices, b.indptr),
                                            shape=b.shape))
    if isinstance(x, SparseCooTensor):
        out = softmax(x.to_sparse_csr(), axis)
        return SparseCooTensor.from_dense(out._b.todense(), x._b.n_sparse)
    return wrap(jax.nn.softmax(_arr(x), axis=axis))


def _dense_ndhwc(x):
    if isinstance(x, SparseCooTensor):
        return x._b.todense()
    return _arr(x)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None):
    """Sparse conv3d: densify -> MXU conv -> re-sparsify.
    Reference: python/paddle/sparse/nn/functional/conv.py conv3d (phi
    sparse conv3d gather-gemm-scatter kernel)."""
    d = _dense_ndhwc(x)
    w = _arr(weight)  # [kd, kh, kw, in/groups, out]
    if isinstance(stride, int):
        stride = (stride,) * 3
    if isinstance(dilation, int):
        dilation = (dilation,) * 3
    if isinstance(padding, int):
        padding = [(padding, padding)] * 3
    elif padding and isinstance(padding[0], int):
        padding = [(p, p) for p in padding]
    dn = lax.conv_dimension_numbers(d.shape, w.shape,
                                    ("NDHWC", "DHWIO", "NDHWC"))
    out = lax.conv_general_dilated(
        d.astype(w.dtype), w, window_strides=stride, padding=padding,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups)
    if bias is not None:
        out = out + _arr(bias)
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor.from_dense(out, 4)  # sparse over N,D,H,W
    return wrap(out, stop_gradient=False)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """Submanifold conv: output sparsity == input sparsity (reference
    SubmConv3D). Computed dense, then masked to input's active sites."""
    out = conv3d(x, weight, bias, stride, padding, dilation, groups,
                 data_format)
    if isinstance(x, SparseCooTensor) and isinstance(out, SparseCooTensor):
        d = x._b.todense()
        active = jnp.any(d != 0, axis=-1, keepdims=True)
        od = out._b.todense()
        if od.shape[:4] == active.shape[:4]:
            od = jnp.where(active, od, 0)
            return SparseCooTensor.from_dense(od, 4)
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NDHWC", name=None):
    d = _dense_ndhwc(x)
    if isinstance(kernel_size, int):
        kernel_size = (kernel_size,) * 3
    stride = stride or kernel_size
    if isinstance(stride, int):
        stride = (stride,) * 3
    if isinstance(padding, int):
        padding = [(padding, padding)] * 3
    window = (1,) + tuple(kernel_size) + (1,)
    strides = (1,) + tuple(stride) + (1,)
    pads = [(0, 0)] + list(padding) + [(0, 0)]
    out = lax.reduce_window(d, -jnp.inf, lax.max, window, strides, pads)
    out = jnp.where(jnp.isneginf(out), 0, out)
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor.from_dense(out, 4)
    return wrap(out, stop_gradient=False)


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse-mask attention (reference:
    python/paddle/sparse/nn/functional/transformer.py attention — softmax of
    QK^T restricted to a CSR mask's sparsity, then @ V)."""
    q, k, v = _arr(query), _arr(key), _arr(value)
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    md = sparse_mask.to_dense() if _is_sparse(sparse_mask) else sparse_mask
    md = unwrap(md) if isinstance(md, Tensor) else jnp.asarray(md)
    md = jnp.broadcast_to(md.reshape((-1,) + md.shape[-2:])
                          .reshape(scores.shape[0], -1, *md.shape[-2:])
                          if md.ndim > 2 else md, scores.shape)
    neg = jnp.asarray(-1e9, scores.dtype)
    if key_padding_mask is not None:
        kp = unwrap(key_padding_mask) if isinstance(key_padding_mask, Tensor)\
            else jnp.asarray(key_padding_mask)
        scores = scores + jnp.where(kp[:, None, None, :] != 0, 0., neg)
    if attn_mask is not None:
        am = unwrap(attn_mask) if isinstance(attn_mask, Tensor) \
            else jnp.asarray(attn_mask)
        scores = scores + jnp.where(am != 0, 0., neg)
    scores = jnp.where(md != 0, scores, neg)
    probs = jax.nn.softmax(scores, axis=-1)
    probs = jnp.where(md != 0, probs, 0)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    return wrap(out, stop_gradient=False)
