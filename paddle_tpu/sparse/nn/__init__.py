"""paddle.sparse.nn parity layers.

Reference: python/paddle/sparse/nn/layer/ (activation.py, conv.py, norm.py,
pooling.py).
"""
from __future__ import annotations

import jax.numpy as jnp

from ...nn.layer import Layer
from ...nn import initializer as I
from ...core.tensor import unwrap, wrap
from .. import SparseCooTensor, _is_sparse
from . import functional as F

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "Softmax", "Conv3D", "SubmConv3D",
           "BatchNorm", "SyncBatchNorm", "MaxPool3D"]


class ReLU(Layer):
    def forward(self, x):
        return F.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return F.relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self._negative_slope)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, self._axis)


class _Conv3D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, subm=False,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * 3
        self._subm = subm
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self.weight = self.create_parameter(
            tuple(kernel_size) + (in_channels // groups, out_channels),
            attr=weight_attr, default_initializer=I.XavierUniform())
        if bias_attr is False:
            self.bias = None
            self._parameters["bias"] = None
        else:
            self.bias = self.create_parameter((out_channels,), attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        fn = F.subm_conv3d if self._subm else F.conv3d
        return fn(x, self.weight, self.bias, self._stride, self._padding,
                  self._dilation, self._groups)


class Conv3D(_Conv3D):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, False, padding_mode,
                         weight_attr, bias_attr, data_format)


class SubmConv3D(_Conv3D):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, key=None,
                 data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, True, padding_mode,
                         weight_attr, bias_attr, data_format)


class BatchNorm(Layer):
    """BatchNorm over the channel (last) axis of a sparse NDHWC tensor,
    computed over stored values (reference sparse/nn/layer/norm.py)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        self._momentum = momentum
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            (num_features,), attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter((num_features,), attr=bias_attr,
                                          is_bias=True)
        self._mean = self.create_buffer("_mean_buf",
                                        jnp.zeros((num_features,)))
        self._variance = self.create_buffer("_var_buf",
                                            jnp.ones((num_features,)))

    def create_buffer(self, name, value):
        self.register_buffer(name, wrap(value))
        return getattr(self, name)

    def forward(self, x):
        sparse_in = _is_sparse(x)
        vals = unwrap(x.values()) if sparse_in else unwrap(x)
        flat = vals.reshape(-1, vals.shape[-1])
        if self.training:
            mean = flat.mean(0)
            var = flat.var(0)
            m = self._momentum  # paddle: running = m*running + (1-m)*batch
            rm = unwrap(getattr(self, "_mean_buf"))
            rv = unwrap(getattr(self, "_var_buf"))
            getattr(self, "_mean_buf").set_value(m * rm + (1 - m) * mean)
            getattr(self, "_var_buf").set_value(m * rv + (1 - m) * var)
        else:
            mean = unwrap(getattr(self, "_mean_buf"))
            var = unwrap(getattr(self, "_var_buf"))
        w, b = unwrap(self.weight), unwrap(self.bias)
        norm = (vals - mean) / jnp.sqrt(var + self._epsilon) * w + b
        if sparse_in:
            return x._map_values(lambda v: norm)
        return wrap(norm, stop_gradient=False)


class SyncBatchNorm(BatchNorm):
    """Cross-replica stats come free under pjit (XLA computes global batch
    stats when the batch axis is sharded) — alias of BatchNorm here."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NDHWC", name=None):
        super().__init__()
        self._kernel_size = kernel_size
        self._stride = stride
        self._padding = padding
        self._ceil_mode = ceil_mode

    def forward(self, x):
        return F.max_pool3d(x, self._kernel_size, self._stride,
                            self._padding, self._ceil_mode)
