"""paddle.sparse parity: COO/CSR sparse tensors + ops.

Reference: python/paddle/sparse/__init__.py (__all__ at :53),
paddle/phi/core/sparse_coo_tensor.h / sparse_csr_tensor.h and the phi sparse
kernels (paddle/phi/kernels/sparse/).

TPU-native design: storage rides `jax.experimental.sparse` (BCOO/BCSR), whose
ops lower to XLA gather/scatter/segment-sum — the TPU has no sparse MXU path,
so ops where sparsity buys nothing (elementwise multiply/divide of two
sparse operands, conv3d) deliberately round-trip through dense XLA ops and
re-sparsify; that IS the fast path on this hardware. Value-wise unary math,
add/subtract (index concat + sum_duplicates) and matmul/masked_matmul
(bcoo_dot_general / bcoo_dot_general_sampled) stay in sparse form.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor, unwrap, wrap
from ..core.dtype import convert_dtype

__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
    "sparse_csr_tensor", "sin", "tan", "asin", "atan", "sinh", "tanh",
    "asinh", "atanh", "sqrt", "square", "log1p", "abs", "pow", "cast", "neg",
    "deg2rad", "rad2deg", "expm1", "mv", "matmul", "masked_matmul", "addmm",
    "add", "subtract", "transpose", "multiply", "divide", "coalesce",
    "is_same_shape", "reshape", "to_sparse_coo", "to_sparse_csr", "to_dense",
]


def _arr(x):
    if isinstance(x, Tensor):
        return unwrap(x)
    return jnp.asarray(x)


class SparseCooTensor:
    """COO sparse tensor. Indices are [sparse_ndim, nnz] (reference layout,
    phi::SparseCooTensor paddle/phi/core/sparse_coo_tensor.h)."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._b = bcoo

    # -- construction ----------------------------------------------------
    @classmethod
    def from_dense(cls, dense, sparse_dim=None):
        d = _arr(dense)
        n = sparse_dim if sparse_dim is not None else d.ndim
        return cls(jsparse.BCOO.fromdense(d, n_dense=d.ndim - n))

    # -- reference accessors --------------------------------------------
    def indices(self):
        return wrap(self._b.indices.T)  # [sparse_ndim, nnz]

    def values(self):
        return wrap(self._b.data, stop_gradient=False)

    def to_dense(self):
        return wrap(self._b.todense(), stop_gradient=False)

    def to_sparse_csr(self):
        return SparseCsrTensor.from_dense(self._b.todense())

    @property
    def shape(self):
        return list(self._b.shape)

    @property
    def dtype(self):
        return self._b.dtype

    @property
    def ndim(self):
        return self._b.ndim

    def nnz(self):
        return int(self._b.nse)

    @property
    def stop_gradient(self):
        return True

    def numpy(self):
        return np.asarray(self._b.todense())

    def coalesce(self):
        return SparseCooTensor(self._b.sum_duplicates())

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")

    def _map_values(self, fn, dtype=None):
        data = fn(self._b.data)
        if dtype is not None:
            data = data.astype(dtype)
        return SparseCooTensor(jsparse.BCOO((data, self._b.indices),
                                            shape=self._b.shape))


class SparseCsrTensor:
    """CSR sparse tensor (2-D, or batched 3-D like the reference).
    Reference: paddle/phi/core/sparse_csr_tensor.h."""

    def __init__(self, bcsr: jsparse.BCSR):
        self._b = bcsr

    @classmethod
    def from_dense(cls, dense):
        d = _arr(dense)
        if d.ndim not in (2, 3):
            raise ValueError("SparseCsrTensor supports 2-D/3-D only, got "
                             f"{d.ndim}-D")
        if d.ndim == 3:
            b = jsparse.BCSR.fromdense(d, n_batch=1)
        else:
            b = jsparse.BCSR.fromdense(d)
        return cls(b)

    def crows(self):
        return wrap(self._b.indptr)

    def cols(self):
        return wrap(self._b.indices)

    def values(self):
        return wrap(self._b.data, stop_gradient=False)

    def to_dense(self):
        return wrap(self._b.todense(), stop_gradient=False)

    def to_sparse_coo(self, sparse_dim=None):
        return SparseCooTensor.from_dense(self._b.todense(), sparse_dim)

    @property
    def shape(self):
        return list(self._b.shape)

    @property
    def dtype(self):
        return self._b.dtype

    @property
    def ndim(self):
        return self._b.ndim

    def nnz(self):
        return int(self._b.nse)

    def numpy(self):
        return np.asarray(self._b.todense())

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")

    def _map_values(self, fn, dtype=None):
        data = fn(self._b.data)
        if dtype is not None:
            data = data.astype(dtype)
        return SparseCsrTensor(jsparse.BCSR(
            (data, self._b.indices, self._b.indptr), shape=self._b.shape))


def _is_sparse(x):
    return isinstance(x, (SparseCooTensor, SparseCsrTensor))


# -- creation ------------------------------------------------------------

def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """Build COO from [sparse_ndim, nnz] indices + values.
    Reference: python/paddle/sparse/creation.py sparse_coo_tensor."""
    idx = _arr(indices).astype(jnp.int32).T  # -> [nnz, ndim]
    vals = _arr(values)
    if dtype is not None:
        vals = vals.astype(convert_dtype(dtype))
    if shape is None:
        upper = jnp.max(idx, axis=0) + 1
        shape = tuple(int(u) for u in np.asarray(upper)) + vals.shape[1:]
    return SparseCooTensor(jsparse.BCOO((vals, idx), shape=tuple(shape)))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    vals = _arr(values)
    if dtype is not None:
        vals = vals.astype(convert_dtype(dtype))
    return SparseCsrTensor(jsparse.BCSR(
        (vals, _arr(cols).astype(jnp.int32), _arr(crows).astype(jnp.int32)),
        shape=tuple(shape)))


def to_sparse_coo(x, sparse_dim=None):
    return SparseCooTensor.from_dense(x, sparse_dim)


def to_sparse_csr(x):
    return SparseCsrTensor.from_dense(x)


def to_dense(x):
    return x.to_dense() if _is_sparse(x) else wrap(_arr(x))


# -- unary value math (0 -> 0 preserving; applied to stored values) ------

def _unary(name, fn):
    def op(x, name=None):
        if not _is_sparse(x):
            return wrap(fn(_arr(x)))
        return x._map_values(fn)
    op.__name__ = name
    return op


sin = _unary("sin", jnp.sin)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
atanh = _unary("atanh", jnp.arctanh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
log1p = _unary("log1p", jnp.log1p)
abs = _unary("abs", jnp.abs)  # noqa: A001 - reference name
neg = _unary("neg", jnp.negative)
expm1 = _unary("expm1", jnp.expm1)
deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)


def pow(x, factor, name=None):  # noqa: A001 - reference name
    return x._map_values(lambda v: jnp.power(v, factor))


def cast(x, index_dtype=None, value_dtype=None, name=None):
    out = x
    if value_dtype is not None:
        out = out._map_values(lambda v: v, dtype=convert_dtype(value_dtype))
    # index_dtype: BCOO/BCSR keep int32 internally; accepted for API parity.
    return out


# -- binary --------------------------------------------------------------

def add(x, y, name=None):
    """Sparse+sparse via index concat + sum_duplicates (stays sparse)."""
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        bx, by = x._b, y._b
        data = jnp.concatenate([bx.data, by.data])
        idx = jnp.concatenate([bx.indices, by.indices])
        out = jsparse.BCOO((data, idx), shape=bx.shape).sum_duplicates()
        return SparseCooTensor(out)
    if isinstance(x, SparseCsrTensor) and isinstance(y, SparseCsrTensor):
        s = add(x.to_sparse_coo(), y.to_sparse_coo())
        return s.to_sparse_csr()
    raise TypeError("sparse.add expects two sparse tensors of the same kind")


def subtract(x, y, name=None):
    return add(x, neg(y))


def _dense_binary(x, y, fn):
    # No sparse advantage on the MXU — dense XLA op, then re-sparsify with
    # the union sparsity (matches reference elementwise kernel semantics).
    xd, yd = x.to_dense(), y.to_dense()
    out = fn(unwrap(xd), unwrap(yd))
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor.from_dense(out)
    return SparseCooTensor.from_dense(out, x._b.n_sparse)


def multiply(x, y, name=None):
    return _dense_binary(x, y, jnp.multiply)


def divide(x, y, name=None):
    return _dense_binary(x, y, lambda a, b: jnp.where(
        b == 0, jnp.zeros_like(a), a / jnp.where(b == 0, 1, b)))


# -- linalg --------------------------------------------------------------

def _to_bcoo(x):
    if isinstance(x, SparseCooTensor):
        return x._b
    if isinstance(x, SparseCsrTensor):
        return x._b.to_bcoo()
    return None


def _with_batch(b, nb):
    """Relayout a BCOO so its leading nb dims are batch dims (needed for
    batched dot_general; from_dense builds everything fully sparse)."""
    if nb and b.n_batch < nb:
        # batch-dim storage is padded-dense per batch; acceptable here (the
        # TPU path densifies for the MXU anyway)
        return jsparse.bcoo_update_layout(b, n_batch=nb,
                                          on_inefficient=None)
    return b


def matmul(x, y, name=None):
    """sparse @ dense -> dense (bcoo_dot_general), dense @ sparse likewise,
    sparse @ sparse -> sparse. Reference: sparse/binary.py matmul.
    Batched (3-D) operands relayout leading dims as BCOO batch dims."""
    bx, by = _to_bcoo(x), _to_bcoo(y)
    if bx is not None and by is None:
        yd = _arr(y)
        nb = bx.ndim - 2
        bx = _with_batch(bx, nb)
        dn = (((bx.ndim - 1,), (yd.ndim - 2,)),
              (tuple(range(nb)), tuple(range(nb))))
        out = jsparse.bcoo_dot_general(bx, yd, dimension_numbers=dn)
        return wrap(out, stop_gradient=False)
    if bx is None and by is not None:
        xd = _arr(x)
        nb = by.ndim - 2
        by = _with_batch(by, nb)
        dn = (((by.ndim - 2,), (xd.ndim - 1,)),
              (tuple(range(nb)), tuple(range(nb))))
        out = jsparse.bcoo_dot_general(by, xd, dimension_numbers=dn)
        # result axes: batch..., by_row? -> need transpose of last two
        out = jnp.swapaxes(out, -1, -2)
        return wrap(out, stop_gradient=False)
    if bx is not None and by is not None:
        out = jnp.matmul(bx.todense(), by.todense())
        if isinstance(x, SparseCsrTensor):
            return SparseCsrTensor.from_dense(out)
        return SparseCooTensor.from_dense(out, 2)
    return wrap(jnp.matmul(_arr(x), _arr(y)), stop_gradient=False)


def masked_matmul(x, y, mask, name=None):
    """(dense x dense) sampled at mask's sparsity — XLA's
    bcoo_dot_general_sampled (reference: phi sparse masked_matmul_kernel).
    Batched operands take the dense-product-then-gather path: on TPU the
    MXU computes the full product faster than any sampled kernel, and XLA
    fuses the gather."""
    xd, yd = _arr(x), _arr(y)
    mb = _to_bcoo(mask)
    if mb.n_batch:  # batched CSR masks: flatten to fully-sparse indices
        mb = jsparse.bcoo_update_layout(mb, n_batch=0)
    if xd.ndim == 2:
        dn = (((xd.ndim - 1,), (yd.ndim - 2,)), ((), ()))
        out = jsparse.bcoo_dot_general_sampled(xd, yd, mb.indices,
                                               dimension_numbers=dn)
        res = jsparse.BCOO((out, mb.indices), shape=mb.shape)
    else:
        prod = jnp.matmul(xd, yd)                     # [..., m, n]
        idx = tuple(mb.indices[:, i] for i in range(mb.indices.shape[1]))
        out = prod[idx]                               # sample at mask nnz
        res = jsparse.BCOO((out, mb.indices), shape=mb.shape)
    if isinstance(mask, SparseCsrTensor):
        return SparseCooTensor(res).to_sparse_csr()
    return SparseCooTensor(res)


def mv(x, vec, name=None):
    b = _to_bcoo(x)
    v = _arr(vec)
    out = jsparse.bcoo_dot_general(
        b, v, dimension_numbers=(((1,), (0,)), ((), ())))
    return wrap(out, stop_gradient=False)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    prod = matmul(x, y)
    pd = prod.to_dense() if _is_sparse(prod) else prod
    inp = input.to_dense() if _is_sparse(input) else wrap(_arr(input))
    out = beta * unwrap(inp) + alpha * unwrap(pd)
    if _is_sparse(input):
        if isinstance(input, SparseCsrTensor):
            return SparseCsrTensor.from_dense(out)
        return SparseCooTensor.from_dense(out, input._b.n_sparse)
    return wrap(out, stop_gradient=False)


# -- shape ---------------------------------------------------------------

def transpose(x, perm, name=None):
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(jsparse.bcoo_transpose(
            x._b, permutation=tuple(perm)))
    return SparseCsrTensor.from_dense(
        jnp.transpose(x._b.todense(), tuple(perm)))


def reshape(x, shape, name=None):
    shape = tuple(int(s) for s in shape)
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(jsparse.bcoo_reshape(
            x._b.sum_duplicates(), new_sizes=shape))
    return SparseCsrTensor.from_dense(jnp.reshape(x._b.todense(), shape))


def coalesce(x, name=None):
    return x.coalesce()


def is_same_shape(x, y):
    sx = x.shape if _is_sparse(x) else list(_arr(x).shape)
    sy = y.shape if _is_sparse(y) else list(_arr(y).shape)
    return sx == sy


# dense Tensor bridge methods (reference: paddle.Tensor.to_sparse_coo)
if not hasattr(Tensor, "to_sparse_coo"):
    Tensor.to_sparse_coo = lambda self, sparse_dim=None: \
        SparseCooTensor.from_dense(self, sparse_dim)
    Tensor.to_sparse_csr = lambda self: SparseCsrTensor.from_dense(self)

from . import nn  # noqa: E402,F401
