"""Static-graph collective ops: c_* op insertion for Programs.

Reference: paddle/fluid/operators/collective/ (c_allreduce_sum_op.cc,
c_allgather_op.cc, c_broadcast_op.cc, c_concat_op.cc,
c_softmax_with_cross_entropy, partial ops, ...) — ops inserted into a
static ProgramDesc carrying a ring_id, executed by NCCL at run time.

TPU-native design: the recorded op's fn IS the XLA collective
(lax.psum/all_gather/ppermute) keyed by a mesh axis name instead of a
ring id. A Program containing c_* ops replays to a function with
collective primitives; executing it inside ``shard_map`` over the target
mesh (``run_program_sharded`` below, or any user shard_map) lowers them
to ICI collectives — the compiler plays NCCL's role. Executing on one
device without a mesh raises jax's unbound-axis error, mirroring the
reference's "ring not initialized" failure mode.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .._compat import shard_map as _shard_map
from ..core.tensor import dispatch


def _aval_of(x):
    v = getattr(x, "_value", x)
    return jax.ShapeDtypeStruct(v.shape, v.dtype)


def _nranks(ax):
    from ..parallel.mesh import get_mesh
    from ..utils.enforce import InvalidArgumentError
    m = get_mesh()
    # degree() defaults unknown axes to 1 — require the axis to actually
    # exist in the mesh, else the un-gathered shape would be recorded
    if m is None or ax not in m.degrees:
        raise InvalidArgumentError(
            f"c_* op needs the gather width for axis {ax!r} at build "
            "time: initialize a mesh (paddle_tpu.parallel.init_mesh) "
            "before recording, or pass nranks explicitly",
            hint="a silent nranks=1 would record the un-gathered shape")
    return m.degree(ax)

__all__ = ["c_allreduce_sum", "c_allreduce_max", "c_allreduce_min",
           "c_allgather", "c_broadcast", "c_concat", "c_identity",
           "c_softmax_with_cross_entropy", "run_program_sharded"]


def _axis(ring_id, axis_name):
    # ring_id kept for API parity; the mesh axis is the real key
    return axis_name or "mp"


def c_allreduce_sum(x, ring_id=0, axis_name=None, use_calc_stream=True):
    ax = _axis(ring_id, axis_name)
    return dispatch(lambda v: jax.lax.psum(v, ax), x,
                    name="c_allreduce_sum", static_out_aval=_aval_of(x))


def c_allreduce_max(x, ring_id=0, axis_name=None, use_calc_stream=True):
    ax = _axis(ring_id, axis_name)
    return dispatch(lambda v: jax.lax.pmax(v, ax), x,
                    name="c_allreduce_max", static_out_aval=_aval_of(x))


def c_allreduce_min(x, ring_id=0, axis_name=None, use_calc_stream=True):
    ax = _axis(ring_id, axis_name)
    return dispatch(lambda v: jax.lax.pmin(v, ax), x,
                    name="c_allreduce_min", static_out_aval=_aval_of(x))


def c_allgather(x, nranks=None, ring_id=0, axis_name=None):
    ax = _axis(ring_id, axis_name)
    a = _aval_of(x)
    n = nranks or _nranks(ax)
    out = jax.ShapeDtypeStruct((a.shape[0] * n,) + a.shape[1:], a.dtype)
    return dispatch(lambda v: jax.lax.all_gather(v, ax, axis=0,
                                                 tiled=True), x,
                    name="c_allgather", static_out_aval=out)


def c_broadcast(x, root=0, ring_id=0, axis_name=None):
    ax = _axis(ring_id, axis_name)

    def fn(v):
        # select root's value on every member (psum of masked value)
        idx = jax.lax.axis_index(ax)
        contrib = jnp.where(idx == root, v, jnp.zeros_like(v))
        return jax.lax.psum(contrib, ax)

    return dispatch(fn, x, name="c_broadcast",
                    static_out_aval=_aval_of(x))


def c_concat(x, nranks=None, ring_id=0, axis_name=None):
    """Gather along the LAST axis (reference c_concat_op: TP column
    outputs concatenated)."""
    ax = _axis(ring_id, axis_name)
    a = _aval_of(x)
    n = nranks or _nranks(ax)
    out = jax.ShapeDtypeStruct(a.shape[:-1] + (a.shape[-1] * n,), a.dtype)
    return dispatch(lambda v: jax.lax.all_gather(
        v, ax, axis=len(a.shape) - 1, tiled=True), x, name="c_concat",
        static_out_aval=out)


def c_identity(x, ring_id=0, axis_name=None):
    """Forward identity whose grad is an allreduce (reference
    c_identity_op — the TP input marker)."""
    ax = _axis(ring_id, axis_name)

    @jax.custom_vjp
    def ident(v):
        return v

    def fwd(v):
        return v, None

    def bwd(_, g):
        return (jax.lax.psum(g, ax),)

    ident.defvjp(fwd, bwd)
    return dispatch(ident, x, name="c_identity",
                    static_out_aval=_aval_of(x))


def c_softmax_with_cross_entropy(logits, label, ring_id=0, axis_name=None,
                                 ignore_index=-100):
    """Vocab-sharded softmax CE (reference
    c_softmax_with_cross_entropy_op.cu): each rank holds a vocab slice;
    max/denominator reduce over the axis."""
    ax = _axis(ring_id, axis_name)

    def fn(lg, lb):
        vocab_local = lg.shape[-1]
        rank = jax.lax.axis_index(ax)
        lo = rank * vocab_local
        m = jax.lax.pmax(jnp.max(lg, -1), ax)
        e = jnp.exp(lg - m[..., None])
        denom = jax.lax.psum(jnp.sum(e, -1), ax)
        local_lb = lb - lo
        in_range = (local_lb >= 0) & (local_lb < vocab_local)
        safe_lb = jnp.clip(local_lb, 0, vocab_local - 1)
        picked = jnp.take_along_axis(lg, safe_lb[..., None], -1)[..., 0]
        picked = jnp.where(in_range, picked, 0.0)
        picked = jax.lax.psum(picked, ax)
        loss = jnp.log(denom) + m - picked
        # ignored labels contribute zero loss (reference + eager
        # _ce_hard semantics)
        return jnp.where(lb == ignore_index, 0.0, loss)

    la = _aval_of(logits)
    out = jax.ShapeDtypeStruct(la.shape[:-1], jnp.float32)
    return dispatch(fn, logits, label,
                    name="c_softmax_with_cross_entropy",
                    nondiff_args=(1,), static_out_aval=out)


def run_program_sharded(program, mesh, feed, fetch_list, in_specs,
                        out_specs=None, scope=None, check_vma=False):
    """Execute a Program containing c_* ops under shard_map over `mesh`.

    feed: {name: GLOBAL array}; in_specs: {name: PartitionSpec for its
    shard_map split}; out_specs: {name: PartitionSpec} for each fetch
    (default replicated — correct for post-collective results; fetching
    a still-sharded intermediate needs its real spec or shard_map
    assembles one shard's local value as the global answer; pass
    check_vma=True to have jax verify replication instead of trusting
    the default).
    """
    from jax.sharding import PartitionSpec as P

    from .executor import _referenced_scope_names, _replay, global_scope

    scope = scope or global_scope()
    ops = list(program.global_block.ops)
    fetch_names = [f.name if hasattr(f, "name") else str(f)
                   for f in fetch_list]
    feed_names = list(feed)
    out_specs = out_specs or {}
    scope_names = [n for n in _referenced_scope_names(program, scope)
                   if n not in feed_names]
    scope_vals = [scope._vars[n] for n in scope_names]

    def body(*vals):
        env = dict(zip(feed_names + scope_names, vals))
        _replay(ops, env)
        return tuple(env[n] for n in fetch_names)

    m = mesh.mesh if hasattr(mesh, "mesh") else mesh
    specs = tuple(in_specs.get(n, P()) for n in feed_names) + \
        tuple(P() for _ in scope_names)
    out = _shard_map(body, mesh=m, in_specs=specs,
                        out_specs=tuple(out_specs.get(n, P())
                                        for n in fetch_names),
                        check_vma=check_vma)(
        *[feed[n] for n in feed_names], *scope_vals)
    return list(out)
