"""paddle_tpu.static.nn — layer functions for static-graph programs.

Reference analogue: python/paddle/static/nn (fc, embedding, conv2d,
batch_norm, …). Each creates its parameters via static.create_parameter
(init recorded into the startup program) and records the compute op into the
default main program.
"""
from __future__ import annotations

import numpy as np


def _F():
    from ..nn import functional
    return functional


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    from . import create_parameter
    from ..nn import initializer as I
    in_dim = int(np.prod(x._value.shape[num_flatten_dims:]))
    w = create_parameter([in_dim, size], str(x._value.dtype),
                         default_initializer=weight_attr)
    b = None
    if bias_attr is not False:
        b = create_parameter([size], str(x._value.dtype), is_bias=True,
                             default_initializer=bias_attr or I.Constant(0.0))
    F = _F()
    if len(x._value.shape) > num_flatten_dims + 1:
        import paddle_tpu as pt
        lead = list(x._value.shape[:num_flatten_dims])
        x = pt.reshape(x, lead + [in_dim])
    out = F.linear(x, w, b)
    if activation:
        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32"):
    from . import create_parameter
    from ..nn import initializer as I
    w = create_parameter(list(size), dtype,
                         default_initializer=param_attr or I.Normal(0, 0.02))
    return _F().embedding(input, w, padding_idx=padding_idx)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, data_format="NCHW"):
    from . import create_parameter
    from ..nn import initializer as I
    ks = ([filter_size, filter_size] if isinstance(filter_size, int)
          else list(filter_size))
    in_ch = (input._value.shape[1] if data_format == "NCHW"
             else input._value.shape[-1])
    w = create_parameter([num_filters, in_ch // groups] + ks,
                         str(input._value.dtype),
                         default_initializer=param_attr or I.KaimingUniform())
    b = None
    if bias_attr is not False:
        b = create_parameter([num_filters], str(input._value.dtype),
                             is_bias=True,
                             default_initializer=bias_attr or I.Constant(0.0))
    return _F().conv2d(input, w, b, stride=stride, padding=padding,
                       dilation=dilation, groups=groups,
                       data_format=data_format)


def batch_norm(input, momentum=0.9, epsilon=1e-5, param_attr=None,
               bias_attr=None, data_format="NCHW", is_test=False):
    """Static BN: in inference-style static programs runs with the recorded
    running statistics (created as persistable vars)."""
    from . import create_parameter, create_global_var
    from ..nn import initializer as I
    ch = (input._value.shape[1] if data_format in ("NCHW", "NCL")
          else input._value.shape[-1])
    dt = str(input._value.dtype)
    scale = create_parameter([ch], dt,
                             default_initializer=param_attr or I.Constant(1.0))
    bias = create_parameter([ch], dt, is_bias=True,
                            default_initializer=bias_attr or I.Constant(0.0))
    mean = create_global_var([ch], 0.0, dt, name=None)
    var = create_global_var([ch], 1.0, dt, name=None)
    return _F().batch_norm(input, mean, var, scale, bias, training=False,
                           momentum=momentum, epsilon=epsilon,
                           data_format=data_format)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None):
    from . import create_parameter
    from ..nn import initializer as I
    shape = [int(d) for d in input._value.shape[begin_norm_axis:]]
    dt = str(input._value.dtype)
    w = create_parameter(shape, dt,
                         default_initializer=param_attr or I.Constant(1.0)) \
        if scale else None
    b = create_parameter(shape, dt, is_bias=True,
                         default_initializer=bias_attr or I.Constant(0.0)) \
        if shift else None
    return _F().layer_norm(input, normalized_shape=shape, weight=w, bias=b,
                           epsilon=epsilon)


def dropout(x, dropout_prob=0.5, is_test=False):
    return _F().dropout(x, p=dropout_prob, training=not is_test)


# ----------------------------------------------- round-3 static.nn tail
# (reference python/paddle/static/nn/__init__.py __all__)


def conv3d(input, num_filters, filter_size, stride=1, padding=0,  # noqa: A002
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, data_format="NCDHW", name=None):
    from .nn_shim import apply_act
    from ..nn import Conv3D
    layer = Conv3D(input.shape[1], num_filters, filter_size, stride=stride,
                   padding=padding, dilation=dilation, groups=groups,
                   weight_attr=param_attr, bias_attr=bias_attr)
    return apply_act(layer(input), act)


def conv2d_transpose(input, num_filters, filter_size, stride=1,  # noqa: A002
                     padding=0, output_padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None,
                     output_size=None, data_format="NCHW", name=None):
    from .nn_shim import apply_act
    from ..nn import Conv2DTranspose
    layer = Conv2DTranspose(input.shape[1], num_filters, filter_size,
                            stride=stride, padding=padding,
                            output_padding=output_padding,
                            dilation=dilation, groups=groups,
                            weight_attr=param_attr, bias_attr=bias_attr)
    return apply_act(layer(input), act)


def conv3d_transpose(input, num_filters, filter_size, stride=1,  # noqa: A002
                     padding=0, output_padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None,
                     output_size=None, data_format="NCDHW", name=None):
    from .nn_shim import apply_act
    from ..nn import Conv3DTranspose
    layer = Conv3DTranspose(input.shape[1], num_filters, filter_size,
                            stride=stride, padding=padding,
                            output_padding=output_padding,
                            dilation=dilation, groups=groups,
                            weight_attr=param_attr, bias_attr=bias_attr)
    return apply_act(layer(input), act)


def group_norm(input, groups, epsilon=1e-5, param_attr=None,  # noqa: A002
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    from .nn_shim import apply_act
    from ..nn import GroupNorm
    layer = GroupNorm(groups, input.shape[1], epsilon=epsilon,
                      weight_attr=param_attr, bias_attr=bias_attr)
    return apply_act(layer(input), act)


def instance_norm(input, epsilon=1e-5, param_attr=None,  # noqa: A002
                  bias_attr=None, name=None):
    from ..nn import InstanceNorm2D
    layer = InstanceNorm2D(input.shape[1], epsilon=epsilon,
                           weight_attr=param_attr, bias_attr=bias_attr)
    return layer(input)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,  # noqa: A002
              enable_scale_and_shift=False, name=None, **kwargs):
    """Reference static.nn.data_norm: normalize by running batch stats
    without learned affine (unless enabled)."""
    from ..nn import functional as F
    from .nn_shim import apply_act
    mean = input.mean(axis=0, keepdim=True)
    var = ((input - mean) ** 2).mean(axis=0, keepdim=True)
    out = (input - mean) / (var + epsilon) ** 0.5
    return apply_act(out, act)


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    from ..nn import PReLU
    n = 1 if mode == "all" else x.shape[1]
    return PReLU(num_parameters=n, weight_attr=param_attr)(x)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    from ..nn import SpectralNorm
    layer = SpectralNorm(weight.shape, dim=dim, power_iters=power_iters,
                         eps=eps)
    return layer(weight)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    from ..nn import Bilinear
    from .nn_shim import apply_act
    layer = Bilinear(x.shape[-1], y.shape[-1], size,
                     weight_attr=param_attr, bias_attr=bias_attr)
    return apply_act(layer(x, y), act)


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None,
                  name=None):
    return _deform_conv2d_impl(
        x, offset, mask, num_filters, filter_size, stride, padding,
        dilation, groups, deformable_groups, param_attr, bias_attr)


def _deform_conv2d_impl(x, offset, mask, num_filters, filter_size, stride,
                        padding, dilation, groups, deformable_groups,
                        param_attr, bias_attr):
    """Deformable conv v2 as grid_sample + dense conv (reference
    deformable_conv_op.cu capability, TPU-composed): per-output-location
    sampling offsets warp the input, then a standard conv applies."""
    import paddle_tpu as pt
    from ..nn import Conv2D
    from ..nn import functional as F
    import numpy as np
    kh = kw = filter_size if isinstance(filter_size, int) else None
    if kh is None:
        kh, kw = filter_size
    b, c, h, w = x.shape
    layer = Conv2D(c, num_filters, (kh, kw), stride=stride, padding=padding,
                   dilation=dilation, groups=groups,
                   weight_attr=param_attr, bias_attr=bias_attr)
    # sample each kernel tap position with its offset via grid_sample,
    # then weight by mask and run 1x1-equivalent accumulation through
    # the conv weights: compose as unfold-with-offsets
    oh = (h + 2 * padding - dilation * (kh - 1) - 1) // stride + 1
    ow = (w + 2 * padding - dilation * (kw - 1) - 1) // stride + 1
    base_y = np.arange(oh) * stride - padding
    base_x = np.arange(ow) * stride - padding
    cols = []
    k = 0
    for i in range(kh):
        for j in range(kw):
            # offset channels: [B, 2*K, oh, ow] ordered (y, x) per tap
            dy = offset[:, 2 * k]
            dx = offset[:, 2 * k + 1]
            gy = pt.to_tensor(
                np.broadcast_to(base_y[:, None] + i * dilation,
                                (oh, ow)).astype("float32")) + dy
            gx = pt.to_tensor(
                np.broadcast_to(base_x[None, :] + j * dilation,
                                (oh, ow)).astype("float32")) + dx
            # normalize to [-1, 1] for grid_sample
            gxn = gx * (2.0 / max(w - 1, 1)) - 1.0
            gyn = gy * (2.0 / max(h - 1, 1)) - 1.0
            grid = pt.ops.stack([gxn, gyn], axis=-1)
            samp = F.grid_sample(x, grid, align_corners=True)
            if mask is not None:
                samp = samp * mask[:, k:k + 1]
            cols.append(samp)
            k += 1
    # cols: K tensors [B, C, oh, ow] -> conv weight [F, C, kh, kw] applies
    # as sum_k W[:, :, k] . cols[k]
    wgt = layer.weight  # [F, C/groups, kh, kw]
    out = None
    k = 0
    for i in range(kh):
        for j in range(kw):
            contrib = F.conv2d(cols[k], wgt[:, :, i:i + 1, j:j + 1])
            out = contrib if out is None else out + contrib
            k += 1
    if layer.bias is not None:
        out = out + layer.bias.reshape([1, -1, 1, 1])
    return out


def nce(input, label, num_total_classes, sample_weight=None,  # noqa: A002
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (reference static.nn.nce):
    logistic discrimination of the true class against sampled noise."""
    import numpy as np

    import paddle_tpu as pt
    from ..nn import functional as F
    d = input.shape[-1]
    w = pt.create_parameter([num_total_classes, d], attr=param_attr)
    bvec = pt.create_parameter([num_total_classes], attr=bias_attr,
                               is_bias=True)
    lb = label.reshape([-1])
    pos_logit = (input * w[lb]).sum(axis=-1) + bvec[lb]
    neg_idx = pt.to_tensor(np.random.randint(
        0, num_total_classes, (num_neg_samples,)).astype("int64"))
    neg_logit = input @ w[neg_idx].T + bvec[neg_idx]
    pos_loss = F.binary_cross_entropy_with_logits(
        pos_logit, pt.ones_like(pos_logit))
    neg_loss = F.binary_cross_entropy_with_logits(
        neg_logit, pt.zeros_like(neg_logit))
    # undo BCE's mean over the negatives: NCE sums over noise samples
    return pos_loss + neg_loss * num_neg_samples


def row_conv(input, future_context_size, param_attr=None, act=None):  # noqa: A002
    """Lookahead row convolution (reference static.nn.row_conv): each time
    step mixes the next `future_context_size` steps per feature."""
    import paddle_tpu as pt
    from .nn_shim import apply_act
    d = input.shape[-1]
    k = future_context_size + 1
    w = pt.create_parameter([k, d], attr=param_attr)
    x = input
    acc = None
    for i in range(k):
        if input.ndim == 3:
            shifted = pt.ops.concat(
                [x[:, i:], pt.ops.zeros_like(x[:, :i])], axis=1) if i else x
            term = shifted * w[i]
        else:
            shifted = pt.ops.concat(
                [x[i:], pt.ops.zeros_like(x[:i])], axis=0) if i else x
            term = shifted * w[i]
        acc = term if acc is None else acc + term
    return apply_act(acc, act)


def sparse_embedding(input, size, padding_idx=None, is_test=False,  # noqa: A002
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32", slot=None):
    """PS sparse-table embedding (reference static.nn.sparse_embedding).
    Single-process path: a dense embedding with the same semantics; under
    the PS runtime the table lives in parallel/ps.py."""
    from ..nn import Embedding
    layer = Embedding(size[0], size[1], padding_idx=padding_idx,
                      weight_attr=param_attr)
    return layer(input)


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,  # noqa: A002
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None):
    """1-D sequence convolution over padded batches (the reference's LoD
    sequence ops collapse to dense NLC convs on TPU)."""
    from ..nn import Conv1D
    from .nn_shim import apply_act
    x = input.transpose([0, 2, 1])       # [B, D, T]
    layer = Conv1D(x.shape[1], num_filters, filter_size,
                   stride=filter_stride,
                   padding=(filter_size - 1) // 2 if padding else 0,
                   weight_attr=param_attr, bias_attr=bias_attr)
    return apply_act(layer(x).transpose([0, 2, 1]), act)


def sequence_softmax(input, use_cudnn=False, name=None):  # noqa: A002
    from ..nn import functional as F
    return F.softmax(input, axis=-1)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Reference static.nn.py_func: run a host Python function as an op.
    Eager/trace: the function is applied directly (jax.pure_callback under
    jit is the XLA equivalent; here static programs replay eagerly)."""
    if isinstance(x, (list, tuple)):
        res = func(*x)
    else:
        res = func(x)
    return res


# control flow (reference static/nn/control_flow.py) -------------------


def cond(pred, true_fn=None, false_fn=None, name=None):
    from ..jit.dy2static import convert_ifelse
    return convert_ifelse(pred, true_fn or (lambda: None),
                          false_fn or (lambda: None), ())


def case(pred_fn_pairs, default=None, name=None):
    for pred, fn in pred_fn_pairs:
        import numpy as np

        from ..core.tensor import Tensor
        p = bool(np.asarray(pred.numpy() if isinstance(pred, Tensor)
                            else pred))
        if p:
            return fn()
    return default() if default is not None else None


def switch_case(branch_index, branch_fns, default=None, name=None):
    import numpy as np

    from ..core.tensor import Tensor
    idx = int(np.asarray(branch_index.numpy()
                         if isinstance(branch_index, Tensor)
                         else branch_index))
    fns = dict(branch_fns) if not isinstance(branch_fns, dict) else branch_fns
    fn = fns.get(idx, default)
    return fn() if fn is not None else None


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    from ..jit.dy2static import convert_while_loop
    return convert_while_loop(cond, body, tuple(loop_vars))


# --------------------------------------------------- legacy sequence ops
# (reference static.nn sequence_* — LoD ops; TPU-native equivalents work
# on dense padded [B, T, ...] batches with optional length vectors, which
# is how variable-length data reaches XLA anyway)


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0):  # noqa: A002
    pool_type = pool_type.lower()
    if pool_type == "sum":
        return input.sum(axis=1)
    if pool_type in ("average", "mean", "avg"):
        return input.mean(axis=1)
    if pool_type == "sqrt":
        t = input.shape[1]
        return input.sum(axis=1) * (1.0 / (t ** 0.5))
    if pool_type == "max":
        return input.max(axis=1)
    if pool_type == "last":
        return input[:, -1]
    if pool_type == "first":
        return input[:, 0]
    raise ValueError(f"unknown pool_type {pool_type!r}")


def sequence_concat(input, name=None):  # noqa: A002
    import paddle_tpu as pt
    return pt.ops.concat(list(input), axis=1)


def sequence_first_step(input):  # noqa: A002
    return input[:, 0]


def sequence_last_step(input):  # noqa: A002
    return input[:, -1]


def sequence_slice(input, offset, length, name=None):  # noqa: A002
    import numpy as np

    import paddle_tpu as pt
    from ..core.tensor import Tensor
    off = np.asarray(offset.numpy() if isinstance(offset, Tensor)
                     else offset).reshape(-1)
    ln = np.asarray(length.numpy() if isinstance(length, Tensor)
                    else length).reshape(-1)
    rows = [input[b, int(off[b]):int(off[b]) + int(ln[b])]
            for b in range(input.shape[0])]
    # pad to the max kept length for a dense result
    m = max(int(v) for v in ln)
    padded = []
    for r in rows:
        if r.shape[0] < m:
            import paddle_tpu as pt2
            pad = pt2.ops.zeros([m - r.shape[0]] + list(r.shape[1:]),
                                dtype=r.dtype)
            r = pt2.ops.concat([r, pad], axis=0)
        padded.append(r)
    return pt.ops.stack(padded, axis=0)


def sequence_expand(x, y, ref_level=-1, name=None):
    import paddle_tpu as pt
    reps = y.shape[1] if y.ndim > 1 else 1
    return pt.ops.repeat_interleave(x, reps, axis=0)


def sequence_expand_as(x, y, name=None):
    return sequence_expand(x, y)


def sequence_pad(x, pad_value, maxlen=None, name=None):
    """x already dense [B, T, ...]: returns (x, lengths)."""
    import numpy as np

    import paddle_tpu as pt
    lengths = pt.to_tensor(np.full((x.shape[0],), x.shape[1], np.int64))
    return x, lengths


def sequence_unpad(x, length, name=None):
    import numpy as np

    from ..core.tensor import Tensor
    ln = np.asarray(length.numpy() if isinstance(length, Tensor)
                    else length).reshape(-1)
    m = int(ln.max()) if ln.size else 0
    return x[:, :m]


def sequence_reshape(input, new_dim):  # noqa: A002
    b = input.shape[0]
    return input.reshape([b, -1, new_dim])


def sequence_scatter(input, index, updates, name=None):  # noqa: A002
    import paddle_tpu as pt
    return pt.ops.put_along_axis(input, index, updates, 1, reduce="add")


def sequence_enumerate(input, win_size, pad_value=0, name=None):  # noqa: A002
    """Sliding windows of ids: [B, T] -> [B, T, win_size]."""
    import paddle_tpu as pt
    cols = []
    T = input.shape[-1]
    for i in range(win_size):
        if i == 0:
            cols.append(input)
        else:
            import numpy as np
            pad = pt.ops.full(list(input.shape[:-1]) + [i], pad_value,
                              dtype=input.dtype)
            cols.append(pt.ops.concat([input[..., i:], pad], axis=-1))
    return pt.ops.stack(cols, axis=-1)


def sequence_reverse(x, name=None):
    import paddle_tpu as pt
    return pt.ops.flip(x, axis=[1])


class StaticRNN:
    """Legacy StaticRNN builder (reference fluid StaticRNN). The builder
    API captures the step body symbolically inside a sub-block — that
    legacy protocol is superseded here: use paddle_tpu.nn.SimpleRNN /
    nn.LSTM / nn.GRU (cuDNN-class recurrences, scan-compiled) or
    jax.lax.scan over a cell for custom steps. Instantiating is allowed
    (config introspection); entering step() raises with this guidance."""

    def __init__(self, name=None):
        self.name = name

    def step(self):
        raise NotImplementedError(
            "StaticRNN's sub-block step capture is a fluid-era protocol; "
            "use paddle_tpu.nn.{SimpleRNN,LSTM,GRU} or lax.scan over a "
            "cell (same capability, XLA-compiled)")

    step_input = memory = update_memory = step_output = output = step
