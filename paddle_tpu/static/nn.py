"""paddle_tpu.static.nn — layer functions for static-graph programs.

Reference analogue: python/paddle/static/nn (fc, embedding, conv2d,
batch_norm, …). Each creates its parameters via static.create_parameter
(init recorded into the startup program) and records the compute op into the
default main program.
"""
from __future__ import annotations

import numpy as np


def _F():
    from ..nn import functional
    return functional


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    from . import create_parameter
    from ..nn import initializer as I
    in_dim = int(np.prod(x._value.shape[num_flatten_dims:]))
    w = create_parameter([in_dim, size], str(x._value.dtype),
                         default_initializer=weight_attr)
    b = None
    if bias_attr is not False:
        b = create_parameter([size], str(x._value.dtype), is_bias=True,
                             default_initializer=bias_attr or I.Constant(0.0))
    F = _F()
    if len(x._value.shape) > num_flatten_dims + 1:
        import paddle_tpu as pt
        lead = list(x._value.shape[:num_flatten_dims])
        x = pt.reshape(x, lead + [in_dim])
    out = F.linear(x, w, b)
    if activation:
        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32"):
    from . import create_parameter
    from ..nn import initializer as I
    w = create_parameter(list(size), dtype,
                         default_initializer=param_attr or I.Normal(0, 0.02))
    return _F().embedding(input, w, padding_idx=padding_idx)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, data_format="NCHW"):
    from . import create_parameter
    from ..nn import initializer as I
    ks = ([filter_size, filter_size] if isinstance(filter_size, int)
          else list(filter_size))
    in_ch = (input._value.shape[1] if data_format == "NCHW"
             else input._value.shape[-1])
    w = create_parameter([num_filters, in_ch // groups] + ks,
                         str(input._value.dtype),
                         default_initializer=param_attr or I.KaimingUniform())
    b = None
    if bias_attr is not False:
        b = create_parameter([num_filters], str(input._value.dtype),
                             is_bias=True,
                             default_initializer=bias_attr or I.Constant(0.0))
    return _F().conv2d(input, w, b, stride=stride, padding=padding,
                       dilation=dilation, groups=groups,
                       data_format=data_format)


def batch_norm(input, momentum=0.9, epsilon=1e-5, param_attr=None,
               bias_attr=None, data_format="NCHW", is_test=False):
    """Static BN: in inference-style static programs runs with the recorded
    running statistics (created as persistable vars)."""
    from . import create_parameter, create_global_var
    from ..nn import initializer as I
    ch = (input._value.shape[1] if data_format in ("NCHW", "NCL")
          else input._value.shape[-1])
    dt = str(input._value.dtype)
    scale = create_parameter([ch], dt,
                             default_initializer=param_attr or I.Constant(1.0))
    bias = create_parameter([ch], dt, is_bias=True,
                            default_initializer=bias_attr or I.Constant(0.0))
    mean = create_global_var([ch], 0.0, dt, name=None)
    var = create_global_var([ch], 1.0, dt, name=None)
    return _F().batch_norm(input, mean, var, scale, bias, training=False,
                           momentum=momentum, epsilon=epsilon,
                           data_format=data_format)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None):
    from . import create_parameter
    from ..nn import initializer as I
    shape = [int(d) for d in input._value.shape[begin_norm_axis:]]
    dt = str(input._value.dtype)
    w = create_parameter(shape, dt,
                         default_initializer=param_attr or I.Constant(1.0)) \
        if scale else None
    b = create_parameter(shape, dt, is_bias=True,
                         default_initializer=bias_attr or I.Constant(0.0)) \
        if shift else None
    return _F().layer_norm(input, normalized_shape=shape, weight=w, bias=b,
                           epsilon=epsilon)


def dropout(x, dropout_prob=0.5, is_test=False):
    return _F().dropout(x, p=dropout_prob, training=not is_test)
