"""Program-building API: data/InputSpec/parameters, append_backward,
gradients, compiled-program & strategy shells, EMA (reference
python/paddle/static/__init__.py + framework.py surfaces)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtype import convert_dtype as to_jax_dtype
from ..utils import unique_name
from .executor import Executor, Scope, global_scope, scope_guard  # noqa: F401
from .graph import (Program, Variable, VarRef, default_main_program,  # noqa: F401
                    default_startup_program, in_static_build, program_guard)


class InputSpec:
    """Shape/dtype/name spec (python/paddle/static/input.py InputSpec)."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(list(tensor.shape), str(tensor.dtype), name)

    def to_aval(self):
        shape = [1 if (d is None or d == -1) else int(d) for d in self.shape]
        return jax.ShapeDtypeStruct(tuple(shape), to_jax_dtype(self.dtype))

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name})")


def data(name, shape, dtype="float32", lod_level=0):
    """Declare a feed Variable in the default main program."""
    prog = default_main_program()
    spec = InputSpec(shape, dtype, name)
    v = prog.global_block.create_var(spec.to_aval(), name=name, is_data=True)
    v._input_spec = spec  # original (possibly dynamic) dims, for export
    if name not in prog._feed_names:
        prog._feed_names.append(name)
    return v


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Persistable trainable var; its init op is recorded into the startup
    program (paddle.static.create_parameter)."""
    from ..nn import initializer as I
    init = default_initializer or (I.Constant(0.0) if is_bias
                                   else I.XavierUniform())
    name = name or unique_name.generate("param")
    value = init(list(shape), dtype)
    from ..core.tensor import unwrap
    raw = unwrap(value)

    main, startup = default_main_program(), default_startup_program()
    v = main.global_block.create_var(
        jax.ShapeDtypeStruct(raw.shape, raw.dtype), name=name,
        persistable=True, trainable=True)
    if name not in main._param_names:
        main._param_names.append(name)
    from .graph import OpDesc
    startup.global_block.append_op(OpDesc(
        "fill_parameter", lambda _v=raw: _v, [], {}, [name],
        jax.tree_util.tree_structure(raw)))
    sv = startup.global_block.create_var(
        jax.ShapeDtypeStruct(raw.shape, raw.dtype), name=name,
        persistable=True)
    startup.global_block.vars[name] = sv
    startup._version += 1
    return v


def create_global_var(shape, value, dtype="float32", persistable=True,
                      name=None):
    name = name or unique_name.generate("global_var")
    raw = jnp.full(tuple(shape), value, to_jax_dtype(dtype))
    main = default_main_program()
    v = main.global_block.create_var(
        jax.ShapeDtypeStruct(raw.shape, raw.dtype), name=name,
        persistable=persistable)
    global_scope()._vars[name] = raw
    return v


def run_startup(exe=None, startup_program=None):
    """Materialize startup-program vars into the scope (Executor.run(startup))."""
    prog = startup_program or default_startup_program()
    from .executor import _replay
    env = _replay(list(prog.global_block.ops), {})
    scope = global_scope()
    for n, v in env.items():
        var = prog.global_block.vars.get(n)
        if var is None or var.persistable:
            scope._vars[n] = jnp.asarray(v)


# Executor.run(startup_program) path: startup programs have no feeds/fetches,
# so Executor.run special-cases them via this hook.
_orig_exe_run = Executor.run


def _exe_run(self, program=None, feed=None, fetch_list=None, **kwargs):
    prog = program or default_main_program()
    if (not fetch_list and not feed and prog._train_spec is None
            and any(op.op_type == "fill_parameter"
                    for op in prog.global_block.ops)):
        run_startup(self, prog)
        return []
    return _orig_exe_run(self, program=program, feed=feed,
                         fetch_list=fetch_list, **kwargs)


Executor.run = _exe_run


def append_backward(loss, parameter_list=None, no_grad_set=None):
    """Register grad computation for trainable params; returns
    [(param_var, grad_var)] (paddle.static.append_backward). The actual
    jax.grad happens at Executor compile time."""
    prog = loss.block.program if getattr(loss, "block", None) is not None \
        else default_main_program()
    block = prog.global_block
    if parameter_list:
        wrt = [p if isinstance(p, str) else p.name for p in parameter_list]
    else:
        wrt = list(prog._param_names)
    if no_grad_set:
        drop = {p if isinstance(p, str) else p.name for p in no_grad_set}
        wrt = [n for n in wrt if n not in drop]
    gnames = [f"{n}@GRAD" for n in wrt]
    for n, g in zip(wrt, gnames):
        src = block.vars[n]
        block.vars[g] = Variable(src._value, name=g, block=block)
    prog._grad_requests.append((loss.name, wrt, gnames))
    prog._version += 1
    return [(block.vars[n], block.vars[g]) for n, g in zip(wrt, gnames)]


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """paddle.static.gradients: d(sum(targets))/d(inputs) as new vars."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    t0 = targets[0]
    prog = t0.block.program if getattr(t0, "block", None) is not None \
        else default_main_program()
    block = prog.global_block
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    outs = []
    for t in targets:
        wrt = [v.name for v in inputs]
        gnames = [unique_name.generate(f"{n}@GRAD") for n in wrt]
        for v, g in zip(inputs, gnames):
            block.vars[g] = Variable(v._value, name=g, block=block)
        prog._grad_requests.append((t.name, wrt, gnames))
        outs.extend(block.vars[g] for g in gnames)
    prog._version += 1
    return outs


def _prune_ops(ops, fetch_names):
    """Backward slice: keep only ops that contribute to the fetch targets
    (reference: Program.prune on save_inference_model)."""
    needed = set(fetch_names)
    kept = []
    for op in reversed(ops):
        if any(o in needed for o in op.outputs):
            kept.append(op)
            needed.update(i.name for i in op.inputs if isinstance(i, VarRef))
    return list(reversed(kept))


def _program_infer_fn(program, feed_names, fetch_names, scope):
    """Pure (feed…) -> fetches closure over scope values, for export.

    Stateful ops (dropout, …) are snapshotted at export: the traced
    function bakes one sample. Export inference programs (is_test /
    training=False) — the reference's save_inference_model likewise
    expects test-mode graphs."""
    from .executor import _replay
    ops = _prune_ops(program.global_block.ops, fetch_names)
    scope_vals = {n: scope._vars[n]
                  for op in ops for n in
                  [i.name for i in op.inputs if isinstance(i, VarRef)]
                  if n in scope._vars}

    def fn(*feed_vals):
        env = dict(scope_vals)
        env.update(zip(feed_names, feed_vals))
        _replay(ops, env)
        return [env[n] for n in fetch_names]

    return fn




class CompiledProgram:
    """Parity shim: compilation happens in Executor's cache already."""

    def __init__(self, program, build_strategy=None):
        self._program = program

    def __getattr__(self, name):
        return getattr(self._program, name)




# ------------------------------------------------- round-3 static tail
# (reference python/paddle/static/__init__.py __all__)


class BuildStrategy:
    """Accepted-and-recorded build options (reference BuildStrategy pybind).
    XLA owns fusion/memory decisions on TPU; the knobs exist for parity."""

    def __init__(self):
        self.enable_inplace = True
        self.fuse_elewise_add_act_ops = False
        self.fuse_bn_act_ops = False
        self.memory_optimize = True
        self.reduce_strategy = 0
        self.gradient_scale_strategy = 0
        self.build_cinn_pass = False
        self.enable_addto = False
        self.enable_sequential_execution = False
        self.num_trainers = 1
        self.trainer_id = 0


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10
        self.num_iteration_per_run = 1
        self.use_thread_barrier = False


class ParallelExecutor:
    """Legacy ParallelExecutor facade (reference fluid ParallelExecutor):
    delegates to the single Executor — XLA SPMD replaces graph replication."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        self._program = main_program or default_main_program()
        self._exe = Executor()

    def run(self, fetch_list=None, feed=None, feed_dict=None,
            return_numpy=True):
        return self._exe.run(self._program, feed=feed or feed_dict,
                             fetch_list=fetch_list,
                             return_numpy=return_numpy)


def Print(input, first_n=-1, message=None, summarize=20,  # noqa: A002
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """static.Print parity: prints at execution via the recorded op."""
    from ..jit.dy2static import convert_print
    convert_print(message or "", input)
    return input


def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None):
    from .nn import py_func as _pf
    return _pf(func, x, out, backward_func, skip_vars_in_backward_input)


def WeightNormParamAttr(dim=None, name=None, initializer=None,
                        learning_rate=1.0, regularizer=None,
                        trainable=True, do_model_average=False,
                        need_clip=True):
    """Weight-normalized ParamAttr (reference WeightNormParamAttr); the
    norm reparameterization applies via nn.utils.weight_norm at layer
    level — here the attr carries the config."""
    from ..nn.param_attr import ParamAttr
    attr = ParamAttr(name=name, initializer=initializer,
                     learning_rate=learning_rate, regularizer=regularizer,
                     trainable=trainable, do_model_average=do_model_average,
                     need_clip=need_clip)
    attr.weight_norm_dim = dim
    return attr


class ExponentialMovingAverage:
    """EMA of parameters (reference static ExponentialMovingAverage):
    update() accumulates; apply()/restore() swap shadow weights."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._shadow = {}
        self._backup = {}

    def update(self, parameters=None):
        from ..core.tensor import unwrap
        params = parameters or _collect_scope_params()
        for p in params:
            key = id(p)
            v = unwrap(p)
            if key not in self._shadow:
                self._shadow[key] = (p, v)
            else:
                _, s = self._shadow[key]
                self._shadow[key] = (p, self._decay * s
                                     + (1 - self._decay) * v)

    def apply(self, executor=None, need_restore=True):
        import contextlib

        from ..core.tensor import unwrap

        @contextlib.contextmanager
        def guard():
            self._backup = {k: unwrap(p) for k, (p, _s)
                            in self._shadow.items()}
            for k, (p, s) in self._shadow.items():
                p._replace_value(s)
            try:
                yield
            finally:
                if need_restore:
                    self.restore()

        return guard()

    def restore(self, executor=None):
        for k, (p, _s) in self._shadow.items():
            if k in self._backup:
                p._replace_value(self._backup[k])
        self._backup = {}


def _collect_scope_params():
    scope = global_scope()
    return [p for p in scope._params.values() if p is not None]


