"""Model save/load + serialization (reference python/paddle/static/io.py):
save/load_inference_model over the StableHLO exporter, program state
save/load, serialize/deserialize surfaces."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtype import convert_dtype as to_jax_dtype
from ..utils import unique_name
from .executor import Executor, Scope, global_scope, scope_guard  # noqa: F401
from .graph import (Program, Variable, VarRef, default_main_program,  # noqa: F401
                    default_startup_program, in_static_build, program_guard)
from .program import _program_infer_fn, _prune_ops  # noqa: F401


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Serialize an inference function as StableHLO + params
    (reference: paddle.static.save_inference_model → __model__ ProgramDesc;
    here the artifact is a jax.export archive consumed by
    paddle_tpu.inference.create_predictor)."""
    from ..inference.export import export_program
    feed_vars = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    if program is None:
        owner = getattr(feed_vars[0], "block", None)
        program = owner.program if owner is not None else default_main_program()
    fetch_vars = fetch_vars if isinstance(fetch_vars, (list, tuple)) else [fetch_vars]
    export_program(path_prefix, program, [v.name for v in feed_vars],
                   [v.name for v in fetch_vars], global_scope())


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Returns (program_like, feed_names, fetch_names); the returned object
    is directly callable via Executor.run-compatible predictor."""
    from ..inference.export import load_exported
    return load_exported(path_prefix)


def save(program, path_prefix):
    """Persist all persistable vars of ``program`` (paddle.static.save)."""
    from ..io.save_load import save as _save
    scope = global_scope()
    names = [n for n, v in program.global_block.vars.items()
             if v.persistable and n in scope._vars]
    _save({n: np.asarray(scope._vars[n]) for n in names},
          path_prefix + ".pdparams")


def load(program, path_prefix, executor=None, var_list=None):
    from ..io.save_load import load as _load
    state = _load(path_prefix + ".pdparams")
    scope = global_scope()
    for n, v in state.items():
        scope._vars[n] = jnp.asarray(np.asarray(v))




def set_program_state(program, state_dict):
    scope = global_scope()
    for n, v in state_dict.items():
        scope._vars[n] = jnp.asarray(np.asarray(v))




# --- program serialization (reference static/io.py) -------------------


def serialize_program(feed_vars, fetch_vars, program=None, **kwargs):
    import pickle
    program = program or default_main_program()
    return pickle.dumps({
        "version": 1,
        "feeds": [v.name for v in feed_vars],
        "fetches": [v.name for v in fetch_vars],
        "desc": [(op.op_type, [getattr(i, "name", None) for i in op.inputs],
                  list(op.outputs))
                 for op in program.global_block.ops],
    })


def serialize_persistables(feed_vars, fetch_vars, executor=None,
                           program=None, **kwargs):
    import pickle

    import numpy as _np
    scope = global_scope()
    state = {n: _np.asarray(scope._vars[n])
             for n in scope.local_var_names()}
    return pickle.dumps(state)


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def deserialize_program(data):
    import pickle
    return pickle.loads(data)


def deserialize_persistables(program, data, executor=None):
    import pickle
    state = pickle.loads(data)
    scope = global_scope()
    for name, val in state.items():
        scope.var(name).set(val)
    return state


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """Reference normalize_program prunes to the feed->fetch subgraph; our
    executor prunes at run time, so normalization is the identity plus
    recording the endpoints."""
    program._normalized_feeds = [v.name for v in feed_vars]
    program._normalized_fetches = [v.name for v in fetch_vars]
    return program


def load_program_state(model_path, var_list=None):
    from ..io.save_load import load as _load
    state = _load(model_path if model_path.endswith(".pdparams")
                  else model_path + ".pdparams")
    return state


