"""Static metric ops (reference python/paddle/static/nn/metric.py):
accuracy/auc/ctr bundle + fluid-era lr decay helper."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtype import convert_dtype as to_jax_dtype
from ..utils import unique_name
from .executor import Executor, Scope, global_scope, scope_guard  # noqa: F401
from .graph import (Program, Variable, VarRef, default_main_program,  # noqa: F401
                    default_startup_program, in_static_build, program_guard)


def accuracy(input, label, k=1, correct=None, total=None):  # noqa: A002
    """static.accuracy op parity: top-k accuracy over a batch."""
    import jax.numpy as jnp

    from ..core.tensor import dispatch

    def fn(logits, lb):
        topk = jnp.argsort(-logits, axis=-1)[..., :k]
        hit = (topk == lb.reshape(-1, 1)).any(-1)
        return hit.mean(dtype=jnp.float32)

    return dispatch(fn, input, label, nondiff_args=(1,), name="accuracy")


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,  # noqa: A002
        slide_steps=1, ins_tag_weight=None):
    """static.auc op parity: returns (auc_value, batch_auc, states...)
    simplified to the AUC value via the rank statistic."""
    import numpy as np

    from ..core.tensor import Tensor
    probs = np.asarray(input.numpy() if isinstance(input, Tensor)
                       else input)
    lb = np.asarray(label.numpy() if isinstance(label, Tensor)
                    else label).reshape(-1)
    pos_scores = probs[:, 1] if probs.ndim == 2 and probs.shape[1] > 1 \
        else probs.reshape(-1)
    order = np.argsort(pos_scores)
    ranks = np.empty_like(order, dtype=np.float64)
    ranks[order] = np.arange(1, len(order) + 1)
    n_pos = (lb == 1).sum()
    n_neg = (lb == 0).sum()
    if n_pos == 0 or n_neg == 0:
        value = 0.0
    else:
        value = (ranks[lb == 1].sum() - n_pos * (n_pos + 1) / 2) \
            / (n_pos * n_neg)
    import paddle_tpu as pt
    v = pt.to_tensor(np.float32(value))
    return v, v, []


def ctr_metric_bundle(input, label, ins_tag_weight=None):  # noqa: A002
    """CTR metrics (reference static.ctr_metric_bundle): returns
    (auc, batch_auc, [stat states])."""
    return auc(input, label)


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """Legacy LR schedule fn -> ExponentialDecay scheduler handle."""
    from ..optimizer.lr import ExponentialDecay
    return ExponentialDecay(learning_rate=learning_rate, gamma=decay_rate)


