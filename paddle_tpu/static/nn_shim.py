"""Shared helper for static.nn act strings."""


def apply_act(x, act):
    if act is None:
        return x
    from ..nn import functional as F
    return getattr(F, act)(x)
