"""Static-graph execution: Scope + Executor over jit-replayed Programs.

Reference analogue: python/paddle/fluid/executor.py:921 (Executor.run →
_ExecutorCache → StandaloneExecutor) backed by C++ InterpreterCore
(paddle/fluid/framework/new_executor/interpretercore.h:62). TPU-native
design: the recorded op list is replayed inside ONE ``jax.jit`` — XLA's
scheduler replaces InterpreterCore's instruction queue/stream analysis, and
the whole train step (forward + grads + optimizer update) compiles to a
single donated XLA program. Results are cached per (program version, feed
signature, fetch list) like _ExecutorCache.
"""
from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp
import numpy as np

from .graph import (Program, Variable, VarRef, default_main_program,
                    op_call_kwargs)


class _VarHolder:
    def __init__(self, scope, name):
        self._scope = scope
        self._name = name

    def get_tensor(self):
        return np.asarray(self._scope._vars[self._name])

    def set(self, value, place=None):
        self._scope._vars[self._name] = jnp.asarray(value)


class Scope:
    """Name → value store for persistable vars (paddle::framework::Scope)."""

    def __init__(self):
        self._vars = {}     # name -> jnp array
        self._params = {}   # name -> eager Parameter (for write-back interop)

    def var(self, name):
        return _VarHolder(self, name)

    def find_var(self, name):
        return _VarHolder(self, name) if name in self._vars else None

    def local_var_names(self):
        return list(self._vars)

    def drop_kids(self):
        pass


_global_scope = Scope()
_scope_stack = [_global_scope]
_ZERO_KEY = None    # lazily built: placeholder key for stateless programs


def _zero_key():
    global _ZERO_KEY
    if _ZERO_KEY is None:
        with jax.ensure_compile_time_eval():
            _ZERO_KEY = jax.random.PRNGKey(0)
    return _ZERO_KEY


def global_scope():
    return _scope_stack[-1]


@contextlib.contextmanager
def scope_guard(scope):
    _scope_stack.append(scope)
    try:
        yield
    finally:
        _scope_stack.pop()


def _replay(ops, env, protect=frozenset(), run_key=None):
    """Replay recorded ops into env. Names in ``protect`` are grad leaves:
    their injected values are never overwritten, and an op is skipped
    entirely only when ALL of its outputs are protected (an op with a
    protected and an unprotected output must still run to produce the
    sibling — skipping it on a partial match dropped sibling outputs).

    ``run_key``: per-run PRNG key. Each op replays inside an rng_scope of
    ``fold_in(run_key, op_index)``, so stateful ops (dropout, ...) draw a
    fresh sample every Executor.run — reference static-graph semantics
    (runtime generator state, not a trace-time frozen sample) — while
    forward and grad replays of the same op stay consistent (the key
    depends only on (run_key, op index), not on replay-local draw order)."""
    from ..core import random as rnd
    from .passes import _stateful
    for idx, op in enumerate(ops):
        outs = set(op.outputs)
        if outs and outs <= protect:
            continue
        vals = [env[i.name] if isinstance(i, VarRef) else i
                for i in op.inputs]
        if run_key is not None and _stateful(op):
            # per-op fold_in only for random ops: stateless ops would
            # trace a dead fold_in each (key index = op index, so the
            # sequence stays stable across replays either way)
            with rnd.rng_scope(jax.random.fold_in(run_key, idx)):
                out = op.fn(*vals, **op_call_kwargs(op))
        else:
            out = op.fn(*vals, **op_call_kwargs(op))
        flat, _ = jax.tree_util.tree_flatten(out)
        for n, v in zip(op.outputs, flat):
            if n not in protect:
                env[n] = v
    return env


def _referenced_scope_names(program, scope):
    names = []
    for op in program.global_block.ops:
        for i in op.inputs:
            if isinstance(i, VarRef) and i.name in scope._vars \
                    and i.name not in names:
                names.append(i.name)
    return names


class Executor:
    """paddle.static.Executor parity; ``place`` is accepted and ignored
    (device placement is jax's default-device / sharding concern)."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}
        self._opt_states = {}   # prog cache key -> (opt_state, step_count)

    def close(self):
        self._cache.clear()

    # ------------------------------------------------------------------
    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True, scope=None, **kwargs):
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        scope = scope or global_scope()

        fetch_names = [f.name if isinstance(f, Variable) else str(f)
                       for f in fetch_list]
        feed_names = sorted(feed.keys())
        feed_vals = [jnp.asarray(np.asarray(feed[n])) for n in feed_names]
        feed_sig = tuple((n, v.shape, str(v.dtype))
                         for n, v in zip(feed_names, feed_vals))

        key = (id(program), program._version, feed_sig, tuple(fetch_names))
        entry = self._cache.get(key)
        if entry is None:
            entry = self._compile(program, scope, feed_names, fetch_names,
                                  key)
            self._cache[key] = entry
        # entry holds the Program strongly so id(program) can't be reused by
        # a collected-and-reallocated Program hitting a stale cache slot
        fn, scope_in_names, train, has_stateful, _prog_ref = entry

        scope_vals = {n: scope._vars[n] for n in scope_in_names}
        # per-run PRNG key: program.random_seed pins determinism (reference
        # Program.random_seed); otherwise draw from the global generator so
        # paddle.seed(...) reproduces run sequences. Deterministic programs
        # must not advance the host generator at all (reference executors
        # only touch generator state for stateful ops).
        from ..core import random as rnd
        if not has_stateful:
            run_key = _zero_key()
        elif getattr(program, "random_seed", 0):
            run_key = jax.random.PRNGKey(int(program.random_seed))
        else:
            run_key = rnd.next_key()
        if train:
            opt, loss_name, pnames = program._train_spec
            # optimizer state is per-program (not per feed-signature): a new
            # batch shape or fetch list must not reset Adam moments
            opt_key = id(program)
            st = self._opt_states.get(opt_key)
            if st is None:
                init_fn, _ = opt.functional()
                pvals = {n: scope._vars[n] for n in pnames}
                st = (init_fn(pvals), 0)
            opt_state, step_count = st
            lr = jnp.asarray(float(opt.get_lr()), jnp.float32)
            fetches, new_persist, new_opt_state = fn(
                feed_vals, scope_vals, opt_state,
                jnp.asarray(step_count + 1, jnp.int32), lr, run_key)
            self._opt_states[opt_key] = (new_opt_state, step_count + 1)
            sched = getattr(opt, "_learning_rate", None)
            if hasattr(sched, "step"):
                sched.step()
        else:
            fetches, new_persist = fn(feed_vals, scope_vals, run_key)

        for n, v in new_persist.items():
            scope._vars[n] = v
            p = scope._params.get(n)
            if p is not None:
                p._replace_value(v)
        if return_numpy:
            fetches = [np.asarray(v) for v in fetches]
        return fetches

    # ------------------------------------------------------------------
    def _compile(self, program, scope, feed_names, fetch_names, key):
        ops = list(program.global_block.ops)
        from .passes import _stateful
        has_stateful = any(_stateful(op) for op in ops)
        block_vars = program.global_block.vars
        scope_in_names = _referenced_scope_names(program, scope)
        persist_out = [n for n in block_vars
                       if block_vars[n].persistable and n in scope._vars]
        train = program._train_spec is not None
        grad_requests = list(program._grad_requests)

        needed_grads = set()
        for tgt, wrt, gnames in grad_requests:
            if any(g in fetch_names for g in gnames):
                needed_grads.update(gnames)

        def build_env(feed_vals, scope_vals):
            env = dict(scope_vals)
            env.update(zip(feed_names, feed_vals))
            return env

        def add_grads(env, run_key):
            for tgt, wrt, gnames in grad_requests:
                if not any(g in needed_grads for g in gnames):
                    continue
                base = dict(env)

                def target_of(wrt_vals, _tgt=tgt, _wrt=wrt, _base=base):
                    e = dict(_base)
                    e.update(zip(_wrt, wrt_vals))
                    # wrt vars are grad leaves: protect the injected
                    # values (else grad w.r.t. an intermediate is 0),
                    # while ops that also produce non-wrt siblings run
                    _replay(ops, e, protect=frozenset(_wrt),
                            run_key=run_key)
                    return e[_tgt].sum()

                gs = jax.grad(target_of)([env[n] for n in wrt])
                for g, gname in zip(gs, gnames):
                    env[gname] = g

        if not train:
            def fn(feed_vals, scope_vals, run_key):
                env = build_env(feed_vals, scope_vals)
                _replay(ops, env, run_key=run_key)
                add_grads(env, run_key)
                fetches = [env[n] for n in fetch_names]
                # a persistable var no op references never enters env
                new_persist = {n: env[n] for n in persist_out if n in env}
                return fetches, new_persist

            return (jax.jit(fn), scope_in_names, False, has_stateful,
                    program)

        opt, loss_name, pnames = program._train_spec
        _, update_fn = opt.functional()
        pnames = list(pnames)

        def train_fn(feed_vals, scope_vals, opt_state, step_i, lr, run_key):
            env = build_env(feed_vals, scope_vals)

            def loss_of(pvals):
                e = dict(env)
                e.update(pvals)
                _replay(ops, e, run_key=run_key)
                return e[loss_name].sum(), e

            (loss, env2), grads = jax.value_and_grad(
                loss_of, has_aux=True)({n: env[n] for n in pnames})
            if opt._grad_clip is not None:
                from ..nn.clip import clip_by_global_norm_tree
                grads, _ = clip_by_global_norm_tree(
                    grads, opt._grad_clip.clip_norm)
            pvals = {n: env[n] for n in pnames}
            new_p, new_state = update_fn(grads, pvals, opt_state, lr=lr,
                                         step=step_i)
            env2.update(new_p)
            for (tgt, wrt, gnames) in grad_requests:
                for w, gname in zip(wrt, gnames):
                    if w in grads:
                        env2[gname] = grads[w]
            fetches = [env2[n] for n in fetch_names]
            new_persist = {n: env2[n] for n in persist_out if n in env2}
            return fetches, new_persist, new_state

        return (jax.jit(train_fn, donate_argnums=(2,)), scope_in_names,
                True, has_stateful, program)
