"""Place/device helpers and IPU shells (reference paddle.static places
API; TPU-native: places are informational — XLA owns placement)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtype import convert_dtype as to_jax_dtype
from ..utils import unique_name
from .executor import Executor, Scope, global_scope, scope_guard  # noqa: F401
from .graph import (Program, Variable, VarRef, default_main_program,  # noqa: F401
                    default_startup_program, in_static_build, program_guard)


def cpu_places(device_count=None):
    n = device_count or 1
    return [f"cpu:{i}" for i in range(n)]


def xpu_places(device_count=None):
    return cpu_places(device_count)


import contextlib as _ctx


@_ctx.contextmanager
def device_guard(device=None):
    yield


@_ctx.contextmanager
def name_scope(prefix=None):
    # Prefix names but keep the *global* uniqueness counters (reference
    # fluid name_scope semantics): two models built under the same scope
    # prefix must not collide in the process-global scope.
    outer = unique_name._generator

    class _Prefixed(unique_name.UniqueNameGenerator):
        def __call__(self, key):
            return outer(f"{prefix or ''}{key}")

    with unique_name.guard(_Prefixed()):
        yield




def cuda_places(device_ids=None):
    return []


def npu_places(device_ids=None):
    return []


def mlu_places(device_ids=None):
    return []


def ipu_shard_guard(index=-1, stage=-1):
    import contextlib
    return contextlib.nullcontext()


class IpuStrategy:
    def __init__(self):
        self.enable_fp16 = False


class IpuCompiledProgram:
    def __init__(self, program=None, ipu_strategy=None, scope=None):
        raise NotImplementedError(
            "IPU backend is not part of the TPU build; use the default "
            "Executor (XLA) path")




def set_ipu_shard(call_func, index=-1, stage=-1):
    return call_func  # IPU sharding has no TPU meaning; identity
