"""paddle_tpu.static — the define-and-run (static graph) API.

Reference analogue: python/paddle/static (Program/Executor/program_guard/
data/InputSpec, save/load_inference_model) over ProgramDesc + C++
InterpreterCore (SURVEY.md L2/L4/L6). TPU-native: a Program records the
JAX callables the eager ops would run; Executor jit-replays them as one
XLA program; inference export is StableHLO via jax.export (see
paddle_tpu.inference). Package layout mirrors the reference:
program.py (builders), io.py, device.py, metrics.py, nn, passes.
"""
from __future__ import annotations

from .executor import Executor, Scope, global_scope, scope_guard  # noqa: F401
from .graph import (Program, Variable, VarRef, default_main_program,  # noqa: F401
                    default_startup_program, in_static_build, program_guard)
from . import nn  # noqa: F401
from . import passes  # noqa: F401
from .passes import apply_build_strategy, apply_pass  # noqa: F401
from . import collective  # noqa: F401
from .program import *  # noqa: F401,F403
from .io import *  # noqa: F401,F403
from .device import *  # noqa: F401,F403
from .metrics import *  # noqa: F401,F403
from .program import _exe_run, _program_infer_fn, _prune_ops  # noqa: F401

__all__ = [
    "Program", "Variable", "Executor", "Scope", "global_scope",
    "scope_guard", "program_guard", "default_main_program",
    "default_startup_program", "data", "InputSpec", "create_parameter",
    "create_global_var", "append_backward", "gradients",
    "save_inference_model", "load_inference_model", "save", "load",
    "CompiledProgram", "cpu_places", "device_guard", "name_scope", "nn",
]
