"""Static graph IR: Program / Variable / OpDesc, built by op interception.

Reference analogue: ProgramDesc/BlockDesc/OpDesc/VarDesc
(paddle/fluid/framework/framework.proto) populated by the Python static API
(python/paddle/static). TPU-native design: instead of a protobuf op graph
interpreted by InterpreterCore, a Program records the exact JAX-traceable
callables the eager ops would have run, with shapes inferred via
``jax.eval_shape``; the Executor jit-replays the op list as ONE XLA program
(paddle_tpu/static/executor.py) — the 253-pass IR optimization layer
(paddle/fluid/framework/ir/) collapses into XLA's own pipeline.
"""
from __future__ import annotations

import contextlib
import functools

import jax
import numpy as np

from ..core.tensor import (Parameter, Tensor, set_static_recorder, unwrap,
                           wrap)
from ..utils import unique_name


class Variable(Tensor):
    """Symbolic tensor in a Program (VarDesc analog). ``_value`` holds a
    jax.ShapeDtypeStruct, so shape/dtype introspection and Tensor methods
    (which route through dispatch and get intercepted) both work."""

    def __init__(self, aval, name=None, persistable=False, trainable=False,
                 is_data=False, block=None):
        self._value = aval
        self.name = name or unique_name.generate("tmp_var")
        self.persistable = persistable
        self.trainable = trainable
        self.is_data = is_data
        self.block = block
        self.stop_gradient = not trainable
        self.grad = None
        self._node = None
        self._out_index = 0

    @property
    def desc(self):
        return self

    def numpy(self):
        scope = _find_scope_value(self.name)
        if scope is not None:
            return np.asarray(scope)
        raise RuntimeError(
            f"Variable {self.name!r} is symbolic; run the program through a "
            "static.Executor and fetch it instead of calling .numpy()")

    def __repr__(self):
        return (f"Variable(name={self.name!r}, shape={list(self._value.shape)},"
                f" dtype={self._value.dtype}, persistable={self.persistable})")


def _find_scope_value(name):
    from .executor import global_scope
    return global_scope()._vars.get(name)


class VarRef:
    """Reference to a named var in the execution environment (vs a literal)."""

    __slots__ = ("name",)

    def __init__(self, name):
        self.name = name

    def __repr__(self):
        return f"VarRef({self.name})"


class OpDesc:
    """One recorded op: a JAX-traceable fn + input refs/literals + attrs."""

    __slots__ = ("op_type", "fn", "inputs", "attrs", "outputs", "out_treedef")

    def __init__(self, op_type, fn, inputs, attrs, outputs, out_treedef):
        self.op_type = op_type
        self.fn = fn
        self.inputs = inputs      # list of VarRef | literal (python/np/jnp)
        self.attrs = attrs        # kwargs dict (static attributes)
        self.outputs = outputs    # list of output var names
        self.out_treedef = out_treedef

    def __repr__(self):
        ins = [i.name if isinstance(i, VarRef) else type(i).__name__
               for i in self.inputs]
        return f"{{Op({self.op_type}) inputs={ins} outputs={self.outputs}}}"


def op_call_kwargs(op):
    """Execution kwargs for an OpDesc: underscore-prefixed attrs are pass
    annotations (static/passes.py), never op arguments."""
    return {k: v for k, v in op.attrs.items() if not k.startswith("_")}


class Block:
    def __init__(self, program, idx=0):
        self.program = program
        self.idx = idx
        self.ops = []
        self.vars = {}

    def var(self, name):
        if name not in self.vars:
            raise ValueError(f"var {name} not in block {self.idx}")
        return self.vars[name]

    def create_var(self, aval, name=None, **kwargs):
        v = Variable(aval, name=name, block=self, **kwargs)
        self.vars[v.name] = v
        return v

    def append_op(self, op):
        self.ops.append(op)


class Program:
    """ProgramDesc analog: blocks of recorded ops + feed/fetch metadata."""

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.random_seed = 0
        self._feed_names = []       # data vars, in declaration order
        self._param_names = []      # persistable trainable vars
        self._grad_requests = []    # (target_name, [wrt names], [grad names])
        self._train_spec = None     # (optimizer, loss_name) from minimize()
        self._version = 0

    @property
    def global_block(self):
        return self.blocks[0]

    # paddle parity: method form
    def current_block(self):
        return self.blocks[0]

    def all_parameters(self):
        return [self.global_block.vars[n] for n in self._param_names]

    def list_vars(self):
        return list(self.global_block.vars.values())

    @property
    def num_ops(self):
        return len(self.global_block.ops)

    def clone(self, for_test=False):
        import copy
        p = Program()
        p.blocks[0].ops = list(self.global_block.ops)
        p.blocks[0].vars = dict(self.global_block.vars)
        p._feed_names = list(self._feed_names)
        p._param_names = list(self._param_names)
        p._grad_requests = [] if for_test else copy.copy(self._grad_requests)
        p._train_spec = None if for_test else self._train_spec
        p.random_seed = self.random_seed
        return p

    def __str__(self):
        lines = [f"Program(ops={self.num_ops}, feeds={self._feed_names}, "
                 f"params={self._param_names})"]
        for op in self.global_block.ops:
            lines.append("  " + repr(op))
        return "\n".join(lines)

    __repr__ = __str__


_default_main = Program()
_default_startup = Program()
_guard_depth = 0


def default_main_program():
    return _default_main


def default_startup_program():
    return _default_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    """Route op recording into the given programs (paddle.static.program_guard)."""
    global _default_main, _default_startup, _guard_depth
    old_main, old_startup = _default_main, _default_startup
    _default_main = main_program
    if startup_program is not None:
        _default_startup = startup_program
    _guard_depth += 1
    _install()
    try:
        yield
    finally:
        _guard_depth -= 1
        _default_main, _default_startup = old_main, old_startup
        if _guard_depth == 0:
            set_static_recorder(None)


def in_static_build():
    return _guard_depth > 0


_static_mode = False


def enable_static_mode():
    """Global static mode (paddle.enable_static): ops on symbolic Variables
    record into default_main_program without an explicit program_guard."""
    global _guard_depth, _static_mode
    if not _static_mode:
        _static_mode = True
        _guard_depth += 1
        _install()


def disable_static_mode():
    """paddle.disable_static parity; no-op when not enabled."""
    global _guard_depth, _static_mode
    if _static_mode:
        _static_mode = False
        _guard_depth -= 1
        if _guard_depth == 0:
            set_static_recorder(None)


def in_static_mode():
    return _static_mode


class _Recorder:
    """dispatch() hook: records ops touching symbolic Variables."""

    def active(self, args):
        return _guard_depth > 0 and any(
            isinstance(a, Variable) for a in args)

    def record(self, fn, args, kwargs, name=None, static_out_aval=None):
        block = _default_main.global_block
        inputs, avals = [], []
        for a in args:
            if isinstance(a, Variable):
                inputs.append(VarRef(a.name))
                avals.append(a._value)
            elif isinstance(a, Parameter):
                ref = _intern_parameter(a, block)
                inputs.append(ref)
                avals.append(jax.ShapeDtypeStruct(
                    a._value.shape, a._value.dtype))
            elif isinstance(a, Tensor):
                v = unwrap(a)
                inputs.append(v)
                avals.append(v)
            else:
                inputs.append(a)
                avals.append(a)
        if static_out_aval is not None:
            # ops that cannot be shape-traced outside their execution
            # context (e.g. c_* collectives need a bound mesh axis)
            # declare their output avals explicitly
            out_avals = static_out_aval
        else:
            out_avals = jax.eval_shape(functools.partial(fn, **kwargs),
                                       *avals)
        flat, treedef = jax.tree_util.tree_flatten(out_avals)
        op_type = name or getattr(fn, "__name__", "op")
        out_vars = [block.create_var(av, name=unique_name.generate(op_type))
                    for av in flat]
        block.append_op(OpDesc(op_type, fn, inputs, dict(kwargs),
                               [v.name for v in out_vars], treedef))
        _default_main._version += 1
        outs = jax.tree_util.tree_unflatten(treedef, out_vars)
        return outs


def _intern_parameter(param, block):
    """A concrete Parameter used under program_guard becomes a persistable
    scope var, so nn.Layer works in static mode and minimize() can find and
    update the weights (reference: parameters live in the Scope)."""
    from .executor import global_scope
    pname = getattr(param, "name", None) or unique_name.generate("param")
    param.name = pname
    prog = _default_main
    if pname not in block.vars:
        v = Variable(
            jax.ShapeDtypeStruct(param._value.shape, param._value.dtype),
            name=pname, persistable=True,
            trainable=not param.stop_gradient, block=block)
        block.vars[pname] = v
        if v.trainable and pname not in prog._param_names:
            prog._param_names.append(pname)
        global_scope()._vars[pname] = unwrap(param)
        global_scope()._params[pname] = param
    return VarRef(pname)


_recorder = _Recorder()


def _install():
    set_static_recorder(_recorder)
