"""Static-graph pass infrastructure.

Reference analogue: paddle/fluid/framework/ir/ (Pass/PassRegistry over the
SSA graph, 253 pass files) and python/paddle/static/apply_pass. TPU-native
scope: XLA owns device-level fusion/layout/scheduling, so the passes that
matter here are PROGRAM-level graph cleanups that shrink what we trace —
dead-op elimination, constant folding, common-subexpression elimination,
and annotation passes. Passes are pure functions Program -> mutated
Program, registered by name.
"""
from __future__ import annotations

import jax

from .graph import OpDesc, Program, VarRef, op_call_kwargs

__all__ = ["PassRegistry", "register_pass", "get_pass", "apply_pass",
           "apply_build_strategy"]


class PassRegistry:
    _passes: dict = {}

    @classmethod
    def register(cls, name, fn):
        cls._passes[name] = fn

    @classmethod
    def get(cls, name):
        if name not in cls._passes:
            raise ValueError(
                f"unknown pass {name!r}; registered: {sorted(cls._passes)}")
        return cls._passes[name]

    @classmethod
    def list(cls):
        return sorted(cls._passes)


def register_pass(name):
    def deco(fn):
        PassRegistry.register(name, fn)
        return fn
    return deco


def get_pass(name):
    return PassRegistry.get(name)


def apply_pass(program, names):
    """paddle.static.apply_pass parity: run the named pass(es) over the
    program's global block, in order."""
    if isinstance(names, str):
        names = [names]
    for n in names:
        PassRegistry.get(n)(program)
        program._version += 1
    return program


def _fetch_roots(program):
    """Names that must stay live: persistables, feeds, declared fetches,
    grad-request outputs. When no fetches were declared
    (normalize_program not called), every unconsumed terminal output is
    a root — pruning would otherwise delete possible fetch targets."""
    roots = set(program._feed_names)
    for name, var in program.global_block.vars.items():
        if getattr(var, "persistable", False):
            roots.add(name)
    for tgt, wrt, gnames in program._grad_requests:
        roots.update(gnames)
        roots.add(tgt)      # jax.grad replays the target's producers
        roots.update(wrt)   # Executor.add_grads reads env[w] for each leaf
    fetches = getattr(program, "_normalized_fetches", None)
    if fetches:
        roots.update(fetches)
    else:
        ops = program.global_block.ops
        consumed = {i.name for op in ops for i in op.inputs
                    if isinstance(i, VarRef)}
        for op in ops:
            roots.update(o for o in op.outputs if o not in consumed)
    return roots


@register_pass("dead_code_elimination")
def dead_code_elimination(program):
    """Drop ops none of whose outputs are consumed downstream or rooted
    (reference ir pass: delete_op / graph_to_program pruning)."""
    block = program.global_block
    roots = _fetch_roots(program)
    live = set(roots)
    # walk backwards: an op is live if any output is live
    kept = []
    for op in reversed(block.ops):
        if any(o in live for o in op.outputs) or not op.outputs:
            kept.append(op)
            for i in op.inputs:
                if isinstance(i, VarRef):
                    live.add(i.name)
        # else: dropped
    kept.reverse()
    removed = len(block.ops) - len(kept)
    block.ops = kept
    return removed


@register_pass("constant_folding")
def constant_folding(program):
    """Execute ops whose inputs are all literals at pass time and replace
    them with the computed constant (reference constant_folding_pass)."""
    block = program.global_block
    const_vals = {}
    new_ops = []
    folded = 0
    # grad-wrt leaves act as variables even when their value is constant:
    # an op consuming one must never fold, or the grad target becomes a
    # pass-time constant and the gradient silently zeroes
    wrt_names = {w for _t, wrt, _g in program._grad_requests for w in wrt}
    for op in block.ops:
        ready = []
        all_const = True
        for i in op.inputs:
            if isinstance(i, VarRef):
                if i.name in const_vals and i.name not in wrt_names:
                    ready.append(const_vals[i.name])
                else:
                    all_const = False
                    break
            else:
                ready.append(i)
        # random/stateful ops must not be executed once at pass time and
        # frozen to a single sample (mirrors the CSE guard and the
        # reference constant_folding_pass persistable/stateful skip)
        if all_const and op.outputs and not _stateful(op):
            try:
                out = op.fn(*ready, **op_call_kwargs(op))
            except Exception:
                new_ops.append(op)
                continue
            flat, _ = jax.tree_util.tree_flatten(out)
            for name, val in zip(op.outputs, flat):
                const_vals[name] = val
            folded += 1
        else:
            new_ops.append(op)
    if not const_vals:
        return 0
    # rewrite remaining ops: replace folded VarRefs with literals (keep
    # grad-wrt leaves as VarRefs — the Executor's grad replay injects and
    # protects the leaf value by NAME)
    for op in new_ops:
        op.inputs = [const_vals.get(i.name, i)
                     if isinstance(i, VarRef) and i.name not in wrt_names
                     else i for i in op.inputs]
    # folded names may be fetched (or read as grad leaves): re-emit a
    # constant producer for rooted ones so Executor.run still finds a
    # producing op (same pattern as CSE's share_data identity ops).
    # PREPENDED: consumers that kept a VarRef (wrt leaves) replay later.
    roots = _fetch_roots(program)
    const_ops = [OpDesc("share_data", lambda v: v, [val], {}, [name],
                        jax.tree_util.tree_structure(0))
                 for name, val in const_vals.items() if name in roots]
    block.ops = const_ops + new_ops
    return folded


def _input_key(i):
    if isinstance(i, VarRef):
        return ("ref", i.name)
    try:
        hash(i)
        return ("lit", i)
    except TypeError:
        return ("obj", id(i))


def _norm_attr(v):
    """Hashable, equality-faithful normal form for attr values: containers
    normalize recursively; ndarrays by exact bytes; other unhashables fall
    back to identity (never merged — safe)."""
    import numpy as _np
    if isinstance(v, (list, tuple)):
        return ("seq", tuple(_norm_attr(e) for e in v))
    if isinstance(v, dict):
        # sort by repr of the key: mixed-type keys are not orderable
        return ("map", tuple(sorted(((repr(k), _norm_attr(x))
                                     for k, x in v.items()))))
    if isinstance(v, _np.ndarray):
        return ("nd", v.shape, str(v.dtype), v.tobytes())
    try:
        hash(v)
        return ("lit", v)
    except TypeError:
        return ("id", id(v))


@register_pass("common_subexpression_elimination")
def common_subexpression_elimination(program):
    """Merge identical (op_type, inputs, attrs) ops — later duplicates
    reuse the first op's outputs (reference: ir CSE / fuse passes do this
    structurally; XLA also CSEs, but pruning here shrinks the trace)."""
    block = program.global_block
    seen = {}
    alias = {}
    new_ops = []
    merged = 0
    for op in block.ops:
        ins = tuple(_input_key(alias.get(i.name, i)
                               if isinstance(i, VarRef) else i)
                    for i in op.inputs)
        # normalized attrs: hashable AND equality-faithful (repr would
        # collide on truncated ndarray prints; identity fallback never
        # merges distinct unhashable objects)
        key = (op.op_type, ins,
               tuple(sorted((k, _norm_attr(v))
                            for k, v in op.attrs.items())))
        prev = seen.get(key)
        # random/stateful ops must never merge
        if prev is not None and not _stateful(op):
            for mine, theirs in zip(op.outputs, prev.outputs):
                alias[mine] = VarRef(theirs)
            merged += 1
            continue
        seen[key] = op
        new_ops.append(op)
    if alias:
        for op in new_ops:
            op.inputs = [alias.get(i.name, i) if isinstance(i, VarRef)
                         else i for i in op.inputs]
        # aliased names may be fetched: emit identity ops for rooted ones
        roots = _fetch_roots(program)
        for old, ref in alias.items():
            if old in roots:
                new_ops.append(OpDesc("share_data", lambda v: v,
                                      [ref], {}, [old],
                                      jax.tree_util.tree_structure(0)))
    block.ops = new_ops
    return merged


_STATEFUL_PREFIXES = ("rand", "uniform", "normal", "dropout", "bernoulli",
                      "poisson", "multinomial", "exponential", "seed",
                      "gumbel", "shuffle", "rrelu")


def _stateful(op):
    t = op.op_type.lower()
    return any(t.startswith(p) or p in t for p in _STATEFUL_PREFIXES)


@register_pass("fuse_elewise_add_act")
def fuse_elewise_add_act(program):
    """Annotation pass (reference fuse_elewise_add_act_pass): tags
    add→activation pairs. XLA performs the actual fusion; the tag records
    intent and lets tooling count fusion opportunities."""
    block = program.global_block
    producers = {}
    for op in block.ops:
        for o in op.outputs:
            producers[o] = op
    acts = {"relu", "gelu", "sigmoid", "tanh", "silu"}
    tagged = 0
    for op in block.ops:
        if op.op_type in acts and op.inputs:
            i0 = op.inputs[0]
            if isinstance(i0, VarRef):
                p = producers.get(i0.name)
                if p is not None and p.op_type == "add":
                    op.attrs = dict(op.attrs, _fused_with_add=True)
                    tagged += 1
    return tagged


def apply_build_strategy(main_program, startup_program, build_strategy,
                         pass_attrs=None):
    """Reference paddle.static.apply_build_strategy: translate strategy
    flags into pass runs."""
    # DCE is always safe and always beneficial on the recorded program
    names = ["dead_code_elimination"]
    if getattr(build_strategy, "memory_optimize", False):
        names.append("common_subexpression_elimination")
        names.append("constant_folding")
    if getattr(build_strategy, "fuse_elewise_add_act_ops", False):
        names.append("fuse_elewise_add_act")
    return apply_pass(main_program, names)
