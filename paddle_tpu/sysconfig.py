"""paddle.sysconfig parity."""
import os

__all__ = ["get_include", "get_lib"]

_ROOT = os.path.dirname(os.path.abspath(__file__))


def get_include():
    return os.path.join(_ROOT, "runtime", "csrc")


def get_lib():
    return os.path.join(_ROOT, "runtime", "build")
