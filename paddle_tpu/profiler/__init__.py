"""paddle.profiler parity over jax.profiler.

Reference: python/paddle/profiler/profiler.py:79 (ProfilerTarget/states
CLOSED/READY/RECORD), :215 export_chrome_tracing, :650 scheduler; C++ side
host_tracer.cc + CUPTI (SURVEY §5.1). TPU-native: device+host timelines come
from `jax.profiler` (XPlane -> Perfetto/TensorBoard); the scheduler/step API
and RecordEvent are preserved, and a lightweight step timer reports ips like
fleet's timer.py.
"""
from __future__ import annotations

import contextlib
import time

import jax

__all__ = ["Profiler", "ProfilerTarget", "ProfilerState", "RecordEvent",
           "make_scheduler", "export_chrome_tracing", "profiler_step_timer",
           "StepTimer"]


class ProfilerTarget:
    CPU = "cpu"
    GPU = "gpu"
    TPU = "tpu"
    CUSTOM_DEVICE = "custom_device"


class ProfilerState:
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(closed=0, ready=0, record=1, repeat=0, skip_first=0):
    """Reference profiler.py:650 — returns state per step index."""

    def scheduler(step):
        s = step - skip_first
        if s < 0:
            return ProfilerState.CLOSED
        period = closed + ready + record
        if period <= 0:
            return ProfilerState.RECORD
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name, worker_name=None):
    def handler(prof):
        prof.export(dir_name)
    return handler


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False):
        self._scheduler = scheduler if callable(scheduler) else (
            make_scheduler(record=scheduler[1] - scheduler[0],
                           skip_first=scheduler[0])
            if isinstance(scheduler, (tuple, list)) else None)
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._step = 0
        self._state = ProfilerState.CLOSED
        self._dir = None
        self._active = False
        self.timer = StepTimer()

    def start(self):
        self.timer.start()
        if self._timer_only:
            return
        if self._scheduler is None:
            self._begin_trace()

    def _begin_trace(self):
        if not self._active:
            import tempfile
            self._dir = self._dir or tempfile.mkdtemp(prefix="pt_prof_")
            jax.profiler.start_trace(self._dir)
            self._active = True

    def stop(self):
        self.timer.stop()
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            if self._on_trace_ready:
                self._on_trace_ready(self)

    def step(self, num_samples=None):
        self._step += 1
        self.timer.step(num_samples)
        if self._timer_only or self._scheduler is None:
            return
        state = self._scheduler(self._step)
        if state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            self._begin_trace()
        elif self._active:
            jax.profiler.stop_trace()
            self._active = False
            if self._on_trace_ready:
                self._on_trace_ready(self)

    def export(self, path=None, format=None):  # noqa: A002
        return self._dir

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        t = self.timer
        if t.count:
            return (f"steps={t.count} avg_step_ms="
                    f"{1000*t.total_time/max(t.count,1):.2f} "
                    f"ips={t.ips():.1f}")
        return "no steps recorded"

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *a):
        self.stop()


class RecordEvent:
    """Host-side named range (reference platform/profiler RecordEvent RAII).
    Maps to jax.profiler.TraceAnnotation so it lands in the device trace."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._ctx = None

    def begin(self):
        self._ctx = jax.profiler.TraceAnnotation(self.name)
        self._ctx.__enter__()

    def end(self):
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *a):
        self.end()


class StepTimer:
    """Throughput reporter (reference python/paddle/profiler/timer.py used
    by fleet to report ips). ``publish_to(registry)`` bridges every
    ``step()`` into the telemetry subsystem (per-step histogram + ips
    gauge) at zero cost when unattached."""

    def __init__(self):
        self._tele = None
        self.reset()

    def reset(self):
        self.count = 0
        self.samples = 0
        self.total_time = 0.0
        self._t0 = None

    def publish_to(self, registry, prefix="step_timer"):
        """Publish ``<prefix>_seconds`` (histogram) and ``<prefix>_ips``
        (gauge) into a ``telemetry.MetricRegistry`` on every step()."""
        from ..telemetry.training import STEP_BUCKETS
        if registry.enabled:
            self._tele = (
                registry.histogram(f"{prefix}_seconds",
                                   "Per-step wall time",
                                   buckets=STEP_BUCKETS),
                registry.gauge(f"{prefix}_ips",
                               "Items (samples, else steps) per second"))
        return self

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self):
        if self._t0 is not None:
            self.total_time += time.perf_counter() - self._t0
            self._t0 = None

    def step(self, num_samples=None):
        now = time.perf_counter()
        dt = None
        if self._t0 is not None:
            dt = now - self._t0
            self.total_time += dt
        self._t0 = now
        self.count += 1
        if num_samples:
            self.samples += num_samples
        if dt is not None and self._tele is not None:
            hist, gauge = self._tele
            hist.observe(dt)
            gauge.set(self.ips())

    def ips(self):
        if self.total_time <= 0:
            return 0.0
        base = self.samples if self.samples else self.count
        return base / self.total_time


@contextlib.contextmanager
def profiler_step_timer(registry=None, prefix="step_timer"):
    t = StepTimer()
    if registry is not None:
        t.publish_to(registry, prefix)
    t.start()
    yield t
    t.stop()


class SortedKeys:
    """Report sort keys (reference profiler/profiler_statistic.py)."""
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class SummaryView:
    """Report views (reference profiler/profiler.py SummaryView)."""
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def export_protobuf(profiler_result, path):
    """Persist a captured result (reference export_protobuf; the jax trace
    directory is the TPU-native artifact — we record its path)."""
    import json
    with open(path, "w") as f:
        json.dump({"format": "paddle_tpu-trace-pointer",
                   "trace_dir": getattr(profiler_result, "trace_dir",
                                        str(profiler_result))}, f)
    return path


def load_profiler_result(path):
    import json
    with open(path) as f:
        return json.load(f)
