"""Vision datasets (paddle.vision.datasets parity, zero-egress variants).

Reference: python/paddle/vision/datasets/ (MNIST/Cifar/Flowers downloads).
This environment has no network egress, so file-backed datasets load from a
user-supplied path and `FakeData` provides deterministic synthetic samples
for tests/benchmarks (the reference tests use the same pattern).
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ..io.dataloader import Dataset

__all__ = ["FakeData", "MNIST", "Cifar10", "DatasetFolder", "ImageFolder"]


class FakeData(Dataset):
    def __init__(self, num_samples=1000, image_shape=(3, 32, 32),
                 num_classes=10, transform=None, seed=0):
        self.num_samples = num_samples
        self.image_shape = image_shape
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __len__(self):
        return self.num_samples

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed + idx)
        img = rng.rand(*self.image_shape).astype(np.float32)
        label = np.asarray(rng.randint(0, self.num_classes), np.int64)
        if self.transform:
            img = self.transform(img)
        return img, label


class MNIST(Dataset):
    """idx-format loader (reference MNIST minus the downloader)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        self.transform = transform
        if image_path and os.path.exists(image_path):
            self.images = self._read_images(image_path)
            self.labels = self._read_labels(label_path)
        else:
            fake = FakeData(1000 if mode == "train" else 100,
                            (1, 28, 28), 10)
            self.images = np.stack([fake[i][0][0] for i in range(len(fake))])
            self.labels = np.stack([fake[i][1] for i in range(len(fake))])

    @staticmethod
    def _read_images(path):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            _, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(n, rows, cols).astype(np.float32) / 255.0

    @staticmethod
    def _read_labels(path):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            _, n = struct.unpack(">II", f.read(8))
            return np.frombuffer(f.read(), dtype=np.uint8).astype(np.int64)

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx][None]  # [1, 28, 28]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        self.transform = transform
        if data_file and os.path.exists(data_file):
            import pickle
            with open(data_file, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            self.images = d[b"data"].reshape(-1, 3, 32, 32).astype(
                np.float32) / 255.0
            self.labels = np.asarray(d[b"labels"], np.int64)
        else:
            fake = FakeData(1000 if mode == "train" else 100, (3, 32, 32), 10)
            self.images = np.stack([fake[i][0] for i in range(len(fake))])
            self.labels = np.stack([fake[i][1] for i in range(len(fake))])

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".npy")


class DatasetFolder(Dataset):
    def __init__(self, root, loader=None, extensions=IMG_EXTENSIONS,
                 transform=None, is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                if fname.lower().endswith(extensions):
                    self.samples.append((os.path.join(cdir, fname),
                                         self.class_to_idx[c]))

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        try:
            from PIL import Image
            return np.asarray(Image.open(path).convert("RGB"))
        except ImportError as e:
            raise RuntimeError("install PIL or use .npy images") from e

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = self.loader(path)
        if self.transform:
            img = self.transform(img)
        return img, np.asarray(label, np.int64)


class ImageFolder(DatasetFolder):
    def __init__(self, root, loader=None, extensions=IMG_EXTENSIONS,
                 transform=None):
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        self.samples = [os.path.join(root, f) for f in sorted(os.listdir(root))
                        if f.lower().endswith(extensions)]

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform:
            img = self.transform(img)
        return (img,)

    def __len__(self):
        return len(self.samples)


class FashionMNIST(MNIST):
    """Same idx format as MNIST (reference vision/datasets/mnist.py
    FashionMNIST subclass)."""


class Cifar100(Cifar10):
    """CIFAR-100 python pickle format (fine labels)."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        self.transform = transform
        if data_file and os.path.exists(data_file):
            import pickle
            with open(data_file, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            self.images = d[b"data"].reshape(-1, 3, 32, 32).astype(
                np.float32) / 255.0
            self.labels = np.asarray(d[b"fine_labels"], np.int64)
        else:
            fake = FakeData(1000 if mode == "train" else 100, (3, 32, 32),
                            100)
            self.images = np.stack([fake[i][0] for i in range(len(fake))])
            self.labels = np.stack([fake[i][1] for i in range(len(fake))])


class Flowers(Dataset):
    """Oxford-102 flowers layout (jpg folder + labels .mat or fake)."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=False, backend=None):
        self.transform = transform
        if data_file and os.path.isdir(data_file):
            files = sorted(f for f in os.listdir(data_file)
                           if f.lower().endswith((".jpg", ".jpeg")))
            self.samples = [os.path.join(data_file, f) for f in files]
            if label_file:
                from scipy.io import loadmat
                labels = loadmat(label_file)["labels"].reshape(-1) - 1
                if setid_file:
                    key = {"train": "trnid", "valid": "valid",
                           "test": "tstid"}[mode]
                    ids = loadmat(setid_file)[key].reshape(-1) - 1
                    self.samples = [self.samples[i] for i in ids]
                    labels = labels[ids]
                self.labels = labels.astype(np.int64)
            else:
                raise ValueError(
                    "Flowers with real data needs label_file "
                    "(imagelabels.mat); labels cannot be inferred from "
                    "filenames")
        else:
            fake = FakeData(200 if mode == "train" else 50, (3, 64, 64), 102)
            self.images = np.stack([fake[i][0] for i in range(len(fake))])
            self.labels = np.stack([fake[i][1] for i in range(len(fake))])
            self.samples = None

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, idx):
        if self.samples is not None:
            from PIL import Image
            img = np.asarray(Image.open(self.samples[idx]).convert("RGB"),
                             np.float32).transpose(2, 0, 1) / 255.0
        else:
            img = self.images[idx]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]


class VOC2012(Dataset):
    """Pascal VOC 2012 segmentation layout (JPEGImages/ +
    SegmentationClass/); fake data without a data_file."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        self.transform = transform
        self.pairs = []
        if data_file and os.path.isdir(data_file):
            jdir = os.path.join(data_file, "JPEGImages")
            sdir = os.path.join(data_file, "SegmentationClass")
            for f in sorted(os.listdir(sdir)) if os.path.isdir(sdir) else []:
                stem = os.path.splitext(f)[0]
                self.pairs.append((os.path.join(jdir, stem + ".jpg"),
                                   os.path.join(sdir, f)))
            if not self.pairs:
                raise ValueError(
                    f"no segmentation samples under {data_file!r} "
                    "(expected JPEGImages/ + SegmentationClass/)")
        else:
            fake = FakeData(50, (3, 64, 64), 21)
            self.images = np.stack([fake[i][0] for i in range(len(fake))])
            self.masks = np.stack(
                [np.zeros((64, 64), np.int64) for _ in range(len(fake))])

    def __len__(self):
        return len(self.pairs) if self.pairs else len(self.images)

    def __getitem__(self, idx):
        if self.pairs:
            from PIL import Image
            img = np.asarray(Image.open(self.pairs[idx][0]).convert("RGB"),
                             np.float32).transpose(2, 0, 1) / 255.0
            mask = np.asarray(Image.open(self.pairs[idx][1]), np.int64)
        else:
            img, mask = self.images[idx], self.masks[idx]
        if self.transform:
            img = self.transform(img)
        return img, mask


__all__ += ["FashionMNIST", "Cifar100", "Flowers", "VOC2012"]
