"""Vision datasets (paddle.vision.datasets parity, zero-egress variants).

Reference: python/paddle/vision/datasets/ (MNIST/Cifar/Flowers downloads).
This environment has no network egress, so file-backed datasets load from a
user-supplied path and `FakeData` provides deterministic synthetic samples
for tests/benchmarks (the reference tests use the same pattern).
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ..io.dataloader import Dataset

__all__ = ["FakeData", "MNIST", "Cifar10", "DatasetFolder", "ImageFolder"]


class FakeData(Dataset):
    def __init__(self, num_samples=1000, image_shape=(3, 32, 32),
                 num_classes=10, transform=None, seed=0):
        self.num_samples = num_samples
        self.image_shape = image_shape
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __len__(self):
        return self.num_samples

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed + idx)
        img = rng.rand(*self.image_shape).astype(np.float32)
        label = np.asarray(rng.randint(0, self.num_classes), np.int64)
        if self.transform:
            img = self.transform(img)
        return img, label


class MNIST(Dataset):
    """idx-format loader (reference MNIST minus the downloader)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        self.transform = transform
        if image_path and os.path.exists(image_path):
            self.images = self._read_images(image_path)
            self.labels = self._read_labels(label_path)
        else:
            fake = FakeData(1000 if mode == "train" else 100,
                            (1, 28, 28), 10)
            self.images = np.stack([fake[i][0][0] for i in range(len(fake))])
            self.labels = np.stack([fake[i][1] for i in range(len(fake))])

    @staticmethod
    def _read_images(path):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            _, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(n, rows, cols).astype(np.float32) / 255.0

    @staticmethod
    def _read_labels(path):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            _, n = struct.unpack(">II", f.read(8))
            return np.frombuffer(f.read(), dtype=np.uint8).astype(np.int64)

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx][None]  # [1, 28, 28]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        self.transform = transform
        if data_file and os.path.exists(data_file):
            import pickle
            with open(data_file, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            self.images = d[b"data"].reshape(-1, 3, 32, 32).astype(
                np.float32) / 255.0
            self.labels = np.asarray(d[b"labels"], np.int64)
        else:
            fake = FakeData(1000 if mode == "train" else 100, (3, 32, 32), 10)
            self.images = np.stack([fake[i][0] for i in range(len(fake))])
            self.labels = np.stack([fake[i][1] for i in range(len(fake))])

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".npy")


class DatasetFolder(Dataset):
    def __init__(self, root, loader=None, extensions=IMG_EXTENSIONS,
                 transform=None, is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                if fname.lower().endswith(extensions):
                    self.samples.append((os.path.join(cdir, fname),
                                         self.class_to_idx[c]))

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        try:
            from PIL import Image
            return np.asarray(Image.open(path).convert("RGB"))
        except ImportError as e:
            raise RuntimeError("install PIL or use .npy images") from e

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = self.loader(path)
        if self.transform:
            img = self.transform(img)
        return img, np.asarray(label, np.int64)


class ImageFolder(DatasetFolder):
    def __init__(self, root, loader=None, extensions=IMG_EXTENSIONS,
                 transform=None):
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        self.samples = [os.path.join(root, f) for f in sorted(os.listdir(root))
                        if f.lower().endswith(extensions)]

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform:
            img = self.transform(img)
        return (img,)

    def __len__(self):
        return len(self.samples)
