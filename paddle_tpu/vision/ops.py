"""paddle.vision.ops parity: detection operators.

Reference: python/paddle/vision/ops.py over phi kernels
(nms_kernel.cu, roi_align_kernel.cu, yolo_box_op.cu, ...). TPU-native
split: dense, fixed-shape math (roi_align/roi_pool/yolo_box/prior_box/
box_coder/deform_conv2d) is jnp/XLA; data-dependent-size selection ops
(nms, generate_proposals, distribute_fpn_proposals) run host-side numpy —
exactly the part the reference also runs synchronously on tiny tensors.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as pt

from ..core.tensor import Tensor, dispatch, unwrap, wrap

__all__ = ["yolo_loss", "yolo_box", "prior_box", "box_coder",
           "deform_conv2d", "DeformConv2D", "distribute_fpn_proposals",
           "generate_proposals", "read_file", "decode_jpeg", "roi_pool",
           "RoIPool", "psroi_pool", "PSRoIPool", "roi_align", "RoIAlign",
           "nms", "matrix_nms"]


def _np(x):
    return np.asarray(unwrap(x) if isinstance(x, Tensor) else x)


# ------------------------------------------------------------------ NMS


def _iou_matrix(boxes):
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = np.maximum(x2 - x1, 0) * np.maximum(y2 - y1, 0)
    xx1 = np.maximum(x1[:, None], x1[None, :])
    yy1 = np.maximum(y1[:, None], y1[None, :])
    xx2 = np.minimum(x2[:, None], x2[None, :])
    yy2 = np.minimum(y2[:, None], y2[None, :])
    inter = np.maximum(xx2 - xx1, 0) * np.maximum(yy2 - yy1, 0)
    return inter / (area[:, None] + area[None, :] - inter + 1e-10)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Hard NMS (reference vision/ops.py nms): returns kept indices sorted
    by score. Category-aware when category_idxs given."""
    b = _np(boxes).astype(np.float64)
    n = b.shape[0]
    sc = _np(scores).astype(np.float64) if scores is not None else \
        np.arange(n, 0, -1, dtype=np.float64)
    cats = _np(category_idxs) if category_idxs is not None else \
        np.zeros(n, np.int64)
    keep_all = []
    for c in np.unique(cats):
        idx = np.where(cats == c)[0]
        order = np.argsort(-sc[idx])
        iou = _iou_matrix(b[idx])          # category subset only
        kept = []
        suppressed = np.zeros(idx.size, bool)
        for oi in order:
            if suppressed[oi]:
                continue
            kept.append(idx[oi])
            suppressed |= iou[oi] > iou_threshold
            suppressed[oi] = False
        keep_all.extend(kept)
    keep_all = sorted(keep_all, key=lambda i: -sc[i])
    if top_k is not None:
        keep_all = keep_all[:top_k]
    return pt.to_tensor(np.asarray(keep_all, np.int64))


def matrix_nms(bboxes, scores, score_threshold, post_threshold,
               nms_top_k, keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (reference phi matrix_nms): soft decay by max-IoU with
    higher-scored same-class boxes. Single-image path."""
    bb = _np(bboxes)
    sc = _np(scores)
    if bb.ndim == 3:
        bb = bb[0]
    if sc.ndim == 3:
        sc = sc[0]
    outs, idxs = [], []
    C = sc.shape[0]
    for c in range(C):
        if c == background_label:
            continue
        s = sc[c]
        sel = np.where(s > score_threshold)[0]
        if sel.size == 0:
            continue
        order = sel[np.argsort(-s[sel])][:nms_top_k]
        boxes_c = bb[order]
        iou = _iou_matrix(boxes_c)
        iou = np.triu(iou, 1)
        # max_iou[i]: box i's own max overlap with higher-scored boxes —
        # the compensation term, indexed by the SUPPRESSOR row i
        max_iou = iou.max(0, initial=0.0)
        if use_gaussian:
            decay = np.exp(-(iou ** 2 - max_iou[:, None] ** 2)
                           / gaussian_sigma).min(0, initial=1.0)
        else:
            decay = ((1 - iou) / (1 - max_iou[:, None] + 1e-10)
                     ).min(0, initial=1.0)
        new_s = s[order] * decay
        ok = new_s > post_threshold
        for i, o in enumerate(order):
            if ok[i]:
                outs.append([c, new_s[i], *bb[o]])
                idxs.append(o)
    outs = sorted(zip(outs, idxs), key=lambda t: -t[0][1])[:keep_top_k]
    det = np.asarray([o for o, _ in outs], np.float32).reshape(-1, 6)
    index = np.asarray([i for _, i in outs], np.int64)
    res = [pt.to_tensor(det)]
    if return_index:
        res.append(pt.to_tensor(index))
    if return_rois_num:
        res.append(pt.to_tensor(np.asarray([det.shape[0]], np.int32)))
    return tuple(res) if len(res) > 1 else res[0]


# ------------------------------------------------------------- RoI ops


def _roi_align_one(feat, roi, out_h, out_w, spatial_scale, s_y, s_x,
                   aligned):
    """feat [C, H, W]; roi [4] (x1, y1, x2, y2); s_y/s_x static
    samples-per-bin counts."""
    off = 0.5 if aligned else 0.0
    x1 = roi[0] * spatial_scale - off
    y1 = roi[1] * spatial_scale - off
    x2 = roi[2] * spatial_scale - off
    y2 = roi[3] * spatial_scale - off
    # aligned=True permits degenerate rois; unaligned clamps to 1px
    # (reference roi_align_kernel semantics)
    min_sz = 1e-3 if aligned else 1.0
    rw = jnp.maximum(x2 - x1, min_sz)
    rh = jnp.maximum(y2 - y1, min_sz)
    bin_h = rh / out_h
    bin_w = rw / out_w
    # sample points per bin
    ys = y1 + (jnp.arange(out_h)[:, None]
               + (jnp.arange(s_y)[None, :] + 0.5) / s_y
               ) * bin_h                          # [out_h, s_y]
    xs = x1 + (jnp.arange(out_w)[:, None]
               + (jnp.arange(s_x)[None, :] + 0.5) / s_x
               ) * bin_w                          # [out_w, s_x]
    H, W = feat.shape[-2], feat.shape[-1]

    def bilinear(y, x):
        y0 = jnp.clip(jnp.floor(y), 0, H - 1)
        x0 = jnp.clip(jnp.floor(x), 0, W - 1)
        y1_ = jnp.clip(y0 + 1, 0, H - 1)
        x1_ = jnp.clip(x0 + 1, 0, W - 1)
        wy = jnp.clip(y - y0, 0, 1)
        wx = jnp.clip(x - x0, 0, 1)
        v00 = feat[:, y0.astype(int)][:, :, x0.astype(int)]
        v01 = feat[:, y0.astype(int)][:, :, x1_.astype(int)]
        v10 = feat[:, y1_.astype(int)][:, :, x0.astype(int)]
        v11 = feat[:, y1_.astype(int)][:, :, x1_.astype(int)]
        return (v00 * ((1 - wy)[:, None] * (1 - wx)[None, :])
                + v01 * ((1 - wy)[:, None] * wx[None, :])
                + v10 * (wy[:, None] * (1 - wx)[None, :])
                + v11 * (wy[:, None] * wx[None, :]))

    yflat = ys.reshape(-1)                       # [out_h*s_y]
    xflat = xs.reshape(-1)                       # [out_w*s_x]
    vals = bilinear(yflat, xflat)                # [C, out_h*s_y, out_w*s_x]
    C = vals.shape[0]
    vals = vals.reshape(C, out_h, s_y, out_w, s_x)
    return vals.mean((2, 4))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (reference phi roi_align_kernel): bilinear-sampled average
    per bin; differentiable (pure jnp gather math)."""
    if isinstance(output_size, int):
        out_h = out_w = output_size
    else:
        out_h, out_w = output_size
    bn = _np(boxes_num).astype(np.int64)
    batch_of_roi = np.repeat(np.arange(bn.size), bn)
    # sampling_ratio<=0: the reference phi kernel adapts the grid per ROI
    # (ceil(roi_h/pooled_h) x ceil(roi_w/pooled_w)). Grid sizes must be
    # static for XLA, so compute them host-side when the boxes are
    # concrete; under jit tracing AND under the static-graph recorder
    # fall back to a fixed 2x2 grid — a documented approximation, since
    # data-dependent grid sizes cannot trace, and a recorded Program
    # replays with fresh box feeds so record-time boxes must not bake
    # the grid. sampling_ratio>0 needs no host pull at all.
    import jax.core as _jcore
    from ..static.graph import in_static_build
    _bval = unwrap(boxes) if isinstance(boxes, Tensor) else boxes
    if sampling_ratio > 0:
        grids = [(sampling_ratio, sampling_ratio)] * batch_of_roi.size
    elif isinstance(_bval, _jcore.Tracer) or in_static_build():
        grids = [(2, 2)] * batch_of_roi.size
    else:
        bnp = _np(boxes).astype(np.float64).reshape(-1, 4)
        min_sz = 1e-3 if aligned else 1.0
        grids = []
        for i in range(bnp.shape[0]):
            rw = max((bnp[i, 2] - bnp[i, 0]) * spatial_scale, min_sz)
            rh = max((bnp[i, 3] - bnp[i, 1]) * spatial_scale, min_sz)
            grids.append((max(1, int(np.ceil(rh / out_h))),
                          max(1, int(np.ceil(rw / out_w)))))

    def fn(xv, bv):
        outs = []
        for i in range(bv.shape[0]):
            feat = xv[int(batch_of_roi[i])]
            s_y, s_x = grids[i]
            outs.append(_roi_align_one(feat, bv[i], out_h, out_w,
                                       spatial_scale, s_y, s_x, aligned))
        return jnp.stack(outs) if outs else jnp.zeros(
            (0, xv.shape[1], out_h, out_w), xv.dtype)

    return dispatch(fn, x, boxes, name="roi_align")


class RoIAlign(pt.nn.Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    """Max-pool RoI bins (reference phi roi_pool_kernel): exact masked max
    over the full feature map per bin (no window-size cap)."""
    if isinstance(output_size, int):
        out_h = out_w = output_size
    else:
        out_h, out_w = output_size
    bn = _np(boxes_num).astype(np.int64)
    batch_of_roi = np.repeat(np.arange(bn.size), bn)

    def fn(xv, bv):
        H, W = xv.shape[-2], xv.shape[-1]
        yidx = jnp.arange(H)[:, None]
        xidx = jnp.arange(W)[None, :]
        outs = []
        for i in range(bv.shape[0]):
            feat = xv[int(batch_of_roi[i])]
            x1 = jnp.round(bv[i, 0] * spatial_scale)
            y1 = jnp.round(bv[i, 1] * spatial_scale)
            x2 = jnp.maximum(jnp.round(bv[i, 2] * spatial_scale), x1 + 1)
            y2 = jnp.maximum(jnp.round(bv[i, 3] * spatial_scale), y1 + 1)
            bin_h = (y2 - y1) / out_h
            bin_w = (x2 - x1) / out_w
            rows = []
            for r in range(out_h):
                cols = []
                for c in range(out_w):
                    ys = jnp.floor(y1 + r * bin_h)
                    ye = jnp.ceil(y1 + (r + 1) * bin_h)
                    xs = jnp.floor(x1 + c * bin_w)
                    xe = jnp.ceil(x1 + (c + 1) * bin_w)
                    m = ((yidx >= ys) & (yidx < ye)
                         & (xidx >= xs) & (xidx < xe))
                    cols.append(jnp.max(
                        jnp.where(m[None], feat, -jnp.inf), axis=(1, 2)))
                rows.append(jnp.stack(cols, -1))
            outs.append(jnp.stack(rows, -2))
        return jnp.stack(outs) if outs else jnp.zeros(
            (0, xv.shape[1], out_h, out_w), xv.dtype)

    return dispatch(fn, x, boxes, name="roi_pool")


class RoIPool(pt.nn.Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI average pool (reference phi psroi_pool):
    channel block (i, j) serves output bin (i, j)."""
    if isinstance(output_size, int):
        out_h = out_w = output_size
    else:
        out_h, out_w = output_size
    aligned = roi_align(x, boxes, boxes_num, output_size, spatial_scale,
                        sampling_ratio=2, aligned=False)

    def fn(al):
        n, C, H, W = al.shape
        c_out = C // (out_h * out_w)
        # phi layout: input channel (c*out_h + i)*out_w + j serves output
        # channel c at bin (i, j) — channel-major, then bin-major
        al = al.reshape(n, c_out, out_h, out_w, H, W)
        rows = []
        for i in range(out_h):
            cols = [al[:, :, i, j, i, j] for j in range(out_w)]
            rows.append(jnp.stack(cols, -1))       # [n, c_out, out_w]
        return jnp.stack(rows, -2)                 # [n, c_out, out_h, out_w]

    return dispatch(fn, aligned, name="psroi_pool")


class PSRoIPool(pt.nn.Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


# ------------------------------------------------------------- anchors


def prior_box(input, image, min_sizes, max_sizes=None,  # noqa: A002
              aspect_ratios=(1.0,), variance=(0.1, 0.1, 0.2, 0.2),
              flip=False, clip=False, steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """SSD prior boxes (reference phi prior_box)."""
    fh, fw = int(input.shape[-2]), int(input.shape[-1])
    ih, iw = int(image.shape[-2]), int(image.shape[-1])
    step_h = steps[1] or ih / fh
    step_w = steps[0] or iw / fw
    ars = list(aspect_ratios)
    if flip:
        ars = ars + [1.0 / a for a in aspect_ratios if a != 1.0]
    boxes = []
    for i in range(fh):
        for j in range(fw):
            cx = (j + offset) * step_w
            cy = (i + offset) * step_h
            cell = []
            for k, ms in enumerate(min_sizes):
                # reference phi prior_box order: default
                # (min, aspect-ratio boxes, max); with
                # min_max_aspect_ratios_order=True: (min, max, ars)
                min_box = (cx, cy, ms, ms)
                max_box = None
                if max_sizes:
                    sz = (ms * max_sizes[k]) ** 0.5
                    max_box = (cx, cy, sz, sz)
                ar_boxes = [(cx, cy, ms * a ** 0.5, ms / a ** 0.5)
                            for a in ars if abs(a - 1.0) >= 1e-6]
                if min_max_aspect_ratios_order:
                    cell.append(min_box)
                    if max_box:
                        cell.append(max_box)
                    cell.extend(ar_boxes)
                else:
                    cell.append(min_box)
                    cell.extend(ar_boxes)
                    if max_box:
                        cell.append(max_box)
            for (ccx, ccy, bw, bh) in cell:
                boxes.append([(ccx - bw / 2) / iw, (ccy - bh / 2) / ih,
                              (ccx + bw / 2) / iw, (ccy + bh / 2) / ih])
    out = np.asarray(boxes, np.float32).reshape(fh, fw, -1, 4)
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          out.shape).copy()
    return pt.to_tensor(out), pt.to_tensor(var)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Encode/decode boxes against priors (reference phi box_coder)."""
    pb = _np(prior_box).astype(np.float32)
    pv = _np(prior_box_var).astype(np.float32) if prior_box_var is not None \
        else np.ones_like(pb)
    tb = _np(target_box).astype(np.float32)
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    ph = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw * 0.5
    pcy = pb[:, 1] + ph * 0.5
    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tcx = tb[:, 0] + tw * 0.5
        tcy = tb[:, 1] + th * 0.5
        out = np.stack([(tcx - pcx) / pw / pv[:, 0],
                        (tcy - pcy) / ph / pv[:, 1],
                        np.log(tw / pw) / pv[:, 2],
                        np.log(th / ph) / pv[:, 3]], -1)
    else:  # decode_center_size; tb [N, M, 4] or [N, 4]
        if tb.ndim == 2:
            tb = tb[:, None, :]

        def bc(v):
            # axis=0: prior i decodes row i (broadcast over dim 1);
            # axis=1: prior j decodes column j (broadcast over dim 0)
            return v[:, None] if axis == 0 else v[None, :]

        dcx = bc(pv[:, 0]) * tb[..., 0] * bc(pw) + bc(pcx)
        dcy = bc(pv[:, 1]) * tb[..., 1] * bc(ph) + bc(pcy)
        dw = np.exp(bc(pv[:, 2]) * tb[..., 2]) * bc(pw)
        dh = np.exp(bc(pv[:, 3]) * tb[..., 3]) * bc(ph)
        out = np.stack([dcx - dw * 0.5, dcy - dh * 0.5,
                        dcx + dw * 0.5 - norm, dcy + dh * 0.5 - norm], -1)
        out = out.squeeze(1) if out.shape[1] == 1 else out
    return pt.to_tensor(out.astype(np.float32))


# ------------------------------------------------------------- YOLO


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    """Decode YOLOv3 head outputs to boxes/scores (reference phi
    yolo_box kernel)."""
    xv = _np(x).astype(np.float32)
    n, c, h, w = xv.shape
    na = len(anchors) // 2
    an = np.asarray(anchors, np.float32).reshape(na, 2)
    ioup = None
    if iou_aware:
        # reference layout: [N, na*(6+cls), H, W] — first na channels are
        # IoU logits, the rest the standard head
        ioup = xv[:, :na]
        xv = xv[:, na:]
    xv = xv.reshape(n, na, 5 + class_num, h, w)
    gx = np.arange(w, dtype=np.float32)[None, None, None, :]
    gy = np.arange(h, dtype=np.float32)[None, None, :, None]
    sig = lambda v: 1.0 / (1.0 + np.exp(-v))
    bx = (sig(xv[:, :, 0]) * scale_x_y - 0.5 * (scale_x_y - 1) + gx) / w
    by = (sig(xv[:, :, 1]) * scale_x_y - 0.5 * (scale_x_y - 1) + gy) / h
    in_w = downsample_ratio * w
    in_h = downsample_ratio * h
    bw = np.exp(xv[:, :, 2]) * an[None, :, 0, None, None] / in_w
    bh = np.exp(xv[:, :, 3]) * an[None, :, 1, None, None] / in_h
    conf = sig(xv[:, :, 4])
    if ioup is not None:
        conf = conf ** (1.0 - iou_aware_factor) \
            * sig(ioup) ** iou_aware_factor
    probs = sig(xv[:, :, 5:])
    scores = conf[:, :, None] * probs
    isz = _np(img_size).astype(np.float32)            # [N, 2] (h, w)
    imh = isz[:, 0].reshape(n, 1, 1, 1)
    imw = isz[:, 1].reshape(n, 1, 1, 1)
    x1 = (bx - bw / 2) * imw
    y1 = (by - bh / 2) * imh
    x2 = (bx + bw / 2) * imw
    y2 = (by + bh / 2) * imh
    if clip_bbox:
        x1 = np.clip(x1, 0, imw - 1)
        y1 = np.clip(y1, 0, imh - 1)
        x2 = np.clip(x2, 0, imw - 1)
        y2 = np.clip(y2, 0, imh - 1)
    boxes = np.stack([x1, y1, x2, y2], -1).reshape(n, -1, 4)
    keep = conf > conf_thresh
    boxes = boxes * keep.reshape(n, -1, 1)
    # reference zeroes BOTH the box and its scores below conf_thresh
    scores = scores * keep[:, :, None]
    scores = scores.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
    return pt.to_tensor(boxes.astype(np.float32)), \
        pt.to_tensor(scores.astype(np.float32))


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss (reference phi yolov3_loss), simplified dense
    form: coordinate MSE + objectness/class BCE against assigned anchors."""
    shp = x.shape
    n, c, h, w = shp
    na = len(anchor_mask)
    # dense surrogate: push all predictions toward objectness 0 except
    # cells containing a gt center, where coord/class terms apply
    gtb = _np(gt_box)                                  # [N, B, 4] cx cy w h
    gtl = _np(gt_label).astype(np.int64)               # [N, B]
    obj_target = np.zeros((n, na, h, w), np.float32)
    coord_target = np.zeros((n, na, 4, h, w), np.float32)
    cls_target = np.zeros((n, na, class_num, h, w), np.float32)
    for b in range(n):
        for k in range(gtb.shape[1]):
            cx, cy, bw, bh = gtb[b, k]
            if bw <= 0 or bh <= 0:
                continue
            gi = min(int(cx * w), w - 1)
            gj = min(int(cy * h), h - 1)
            obj_target[b, :, gj, gi] = 1.0
            coord_target[b, :, 0, gj, gi] = cx * w - gi
            coord_target[b, :, 1, gj, gi] = cy * h - gj
            cls_target[b, :, gtl[b, k], gj, gi] = 1.0

    def fn(xv):
        xv = xv.reshape(n, na, 5 + class_num, h, w)
        sig = jax.nn.sigmoid
        pred_xy = sig(xv[:, :, 0:2])
        obj_logit = xv[:, :, 4]
        cls_logit = xv[:, :, 5:]
        obj_t = jnp.asarray(obj_target)
        coord_loss = jnp.sum(jnp.square(pred_xy - jnp.asarray(
            coord_target[:, :, 0:2])) * obj_t[:, :, None])
        bce = lambda lg, t: jnp.maximum(lg, 0) - lg * t + jnp.log1p(
            jnp.exp(-jnp.abs(lg)))
        obj_loss = jnp.sum(bce(obj_logit, obj_t))
        cls_loss = jnp.sum(bce(cls_logit, jnp.asarray(cls_target))
                           * obj_t[:, :, None])
        return (coord_loss + obj_loss + cls_loss) / n

    return dispatch(fn, x, name="yolo_loss")


# ---------------------------------------------------------- proposals


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Route RoIs to FPN levels by scale (reference phi
    distribute_fpn_proposals)."""
    rois = _np(fpn_rois).astype(np.float32)
    w = rois[:, 2] - rois[:, 0]
    h = rois[:, 3] - rois[:, 1]
    scale = np.sqrt(np.maximum(w * h, 1e-6))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-6)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    outs, idxs = [], []
    for level in range(min_level, max_level + 1):
        sel = np.where(lvl == level)[0]
        outs.append(pt.to_tensor(rois[sel]))
        idxs.append(sel)
    restore = np.argsort(np.concatenate(idxs)) if idxs else np.array([])
    res_num = [pt.to_tensor(np.asarray([o.shape[0]], np.int32))
               for o in outs] if rois_num is not None else None
    return outs, pt.to_tensor(restore.astype(np.int64).reshape(-1, 1)), \
        res_num


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """RPN proposal generation (reference phi generate_proposals_v2):
    decode anchors + deltas, clip, filter small, NMS. Single image."""
    # scores [A,H,W] -> (H,W,A)-major flat order to pair with deltas/anchors
    sc = _np(scores)[0].transpose(1, 2, 0).reshape(-1)
    deltas = _np(bbox_deltas)[0].transpose(1, 2, 0).reshape(-1, 4)
    an = _np(anchors).reshape(-1, 4)
    var = _np(variances).reshape(-1, 4)
    ih, iw = [float(v) for v in _np(img_size)[0][:2]]
    aw = an[:, 2] - an[:, 0]
    ah = an[:, 3] - an[:, 1]
    acx = an[:, 0] + aw / 2
    acy = an[:, 1] + ah / 2
    dcx = var[:, 0] * deltas[:, 0] * aw + acx
    dcy = var[:, 1] * deltas[:, 1] * ah + acy
    dw = np.exp(np.minimum(var[:, 2] * deltas[:, 2], 10)) * aw
    dh = np.exp(np.minimum(var[:, 3] * deltas[:, 3], 10)) * ah
    boxes = np.stack([dcx - dw / 2, dcy - dh / 2,
                      dcx + dw / 2, dcy + dh / 2], -1)
    boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, iw)
    boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, ih)
    keep = np.where((boxes[:, 2] - boxes[:, 0] >= min_size)
                    & (boxes[:, 3] - boxes[:, 1] >= min_size))[0]
    order = keep[np.argsort(-sc[keep])][:pre_nms_top_n]
    kept = nms(pt.to_tensor(boxes[order]), nms_thresh,
               scores=pt.to_tensor(sc[order])).numpy()[:post_nms_top_n]
    sel = order[kept]
    rois = pt.to_tensor(boxes[sel].astype(np.float32))
    rscores = pt.to_tensor(sc[sel].astype(np.float32))
    if return_rois_num:
        return rois, rscores, pt.to_tensor(
            np.asarray([sel.size], np.int32))
    return rois, rscores


# ------------------------------------------------------------- image IO


def read_file(filename, name=None):
    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    return pt.to_tensor(data)


def decode_jpeg(x, mode="unchanged", name=None):
    """JPEG decode via PIL (reference phi decode_jpeg over nvjpeg)."""
    import io

    from PIL import Image
    raw = _np(x).astype(np.uint8).tobytes()
    img = Image.open(io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    # mode == "unchanged": keep the file's native channel count
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return pt.to_tensor(arr)


# -------------------------------------------------------- deform conv


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 (reference deform_conv2d): composed as
    offset-warped sampling (grid_sample) + weighted accumulation —
    the static.nn path shares this implementation."""
    from ..nn import functional as F
    kh, kw = weight.shape[-2], weight.shape[-1]
    b, c, h, w = x.shape
    st = stride if isinstance(stride, int) else stride[0]
    pd = padding if isinstance(padding, int) else padding[0]
    dl = dilation if isinstance(dilation, int) else dilation[0]
    oh = (h + 2 * pd - dl * (kh - 1) - 1) // st + 1
    ow = (w + 2 * pd - dl * (kw - 1) - 1) // st + 1
    base_y = np.arange(oh) * st - pd
    base_x = np.arange(ow) * st - pd
    K = kh * kw
    dg = deformable_groups
    cg = c // dg                      # input channels per deformable group
    out = None
    k = 0
    for i in range(kh):
        for j in range(kw):
            # per-deformable-group offsets: channel block g owns taps
            # [g*2K : (g+1)*2K]; its offsets warp channels [g*cg:(g+1)*cg]
            samp_parts = []
            for g in range(dg):
                dy = offset[:, 2 * (g * K + k)]
                dx = offset[:, 2 * (g * K + k) + 1]
                gy = pt.to_tensor(np.broadcast_to(
                    base_y[:, None] + i * dl,
                    (oh, ow)).astype("float32")) + dy
                gx = pt.to_tensor(np.broadcast_to(
                    base_x[None, :] + j * dl,
                    (oh, ow)).astype("float32")) + dx
                gxn = gx * (2.0 / max(w - 1, 1)) - 1.0
                gyn = gy * (2.0 / max(h - 1, 1)) - 1.0
                grid = pt.ops.stack([gxn, gyn], axis=-1)
                xs = x[:, g * cg:(g + 1) * cg] if dg > 1 else x
                sp = F.grid_sample(xs, grid, align_corners=True)
                if mask is not None:
                    sp = sp * mask[:, g * K + k:g * K + k + 1]
                samp_parts.append(sp)
            samp = samp_parts[0] if dg == 1 else pt.ops.concat(
                samp_parts, axis=1)
            contrib = F.conv2d(samp, weight[:, :, i:i + 1, j:j + 1],
                               groups=groups)
            out = contrib if out is None else out + contrib
            k += 1
    if bias is not None:
        out = out + bias.reshape([1, -1, 1, 1])
    return out


class DeformConv2D(pt.nn.Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        from ..nn.initializer import XavierNormal
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups) + ks, attr=weight_attr,
            default_initializer=XavierNormal())
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter((out_channels,),
                                              attr=bias_attr, is_bias=True)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.deformable_groups = deformable_groups
        self.groups = groups

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             self.stride, self.padding, self.dilation,
                             self.deformable_groups, self.groups, mask)
