from . import datasets, models, ops, transforms  # noqa: F401
from .models import LeNet, ResNet, resnet18, resnet50, vgg16  # noqa: F401
