"""Vision model zoo (paddle.vision.models parity: LeNet, VGG, ResNet).

Reference: python/paddle/vision/models/. Convs/pools map to lax windows
(nn/functional) which XLA tiles onto the MXU.
"""
from __future__ import annotations

import paddle_tpu.nn as nn

__all__ = ["LeNet", "VGG", "vgg16", "ResNet", "resnet18", "resnet34",
           "resnet50", "resnet101", "BasicBlock", "BottleneckBlock"]


class LeNet(nn.Layer):
    def __init__(self, num_classes=10):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1), nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0), nn.ReLU(),
            nn.MaxPool2D(2, 2))
        self.fc = nn.Sequential(
            nn.Linear(400, 120), nn.Linear(120, 84),
            nn.Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        x = x.flatten(1)
        return self.fc(x)


class VGG(nn.Layer):
    def __init__(self, features, num_classes=1000, with_pool=True):
        super().__init__()
        self.features = features
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((7, 7))
        self.classifier = nn.Sequential(
            nn.Linear(512 * 7 * 7, 4096), nn.ReLU(), nn.Dropout(),
            nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(),
            nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        x = x.flatten(1)
        return self.classifier(x)


def _vgg_layers(cfg, batch_norm=False):
    layers = []
    c_in = 3
    for v in cfg:
        if v == "M":
            layers.append(nn.MaxPool2D(2, 2))
        else:
            layers.append(nn.Conv2D(c_in, v, 3, padding=1))
            if batch_norm:
                layers.append(nn.BatchNorm2D(v))
            layers.append(nn.ReLU())
            c_in = v
    return nn.Sequential(*layers)


def vgg16(pretrained=False, batch_norm=False, **kwargs):
    cfg = [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
           512, 512, 512, "M", 512, 512, 512, "M"]
    return VGG(_vgg_layers(cfg, batch_norm), **kwargs)


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                               bias_attr=False)
        self.bn1 = nn.BatchNorm2D(planes)
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(planes)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64):
        super().__init__()
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False)
        self.bn1 = nn.BatchNorm2D(width)
        self.conv2 = nn.Conv2D(width, width, 3, stride=stride, padding=1,
                               groups=groups, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(width)
        self.conv3 = nn.Conv2D(width, planes * 4, 1, bias_attr=False)
        self.bn3 = nn.BatchNorm2D(planes * 4)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    def __init__(self, block, depth_cfg, num_classes=1000, with_pool=True,
                 groups=1, width_per_group=64):
        super().__init__()
        self.inplanes = 64
        self.groups = groups
        self.base_width = width_per_group
        self.conv1 = nn.Conv2D(3, 64, 7, stride=2, padding=3,
                               bias_attr=False)
        self.bn1 = nn.BatchNorm2D(64)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, depth_cfg[0])
        self.layer2 = self._make_layer(block, 128, depth_cfg[1], stride=2)
        self.layer3 = self._make_layer(block, 256, depth_cfg[2], stride=2)
        self.layer4 = self._make_layer(block, 512, depth_cfg[3], stride=2)
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False),
                nn.BatchNorm2D(planes * block.expansion))
        extra = {}
        if block is BottleneckBlock:
            extra = dict(groups=self.groups, base_width=self.base_width)
        layers = [block(self.inplanes, planes, stride, downsample, **extra)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, **extra))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if self.with_pool:
            x = self.avgpool(x)
        x = x.flatten(1)
        return self.fc(x)


def resnet18(pretrained=False, **kw):
    return ResNet(BasicBlock, [2, 2, 2, 2], **kw)


def resnet34(pretrained=False, **kw):
    return ResNet(BasicBlock, [3, 4, 6, 3], **kw)


def resnet50(pretrained=False, **kw):
    return ResNet(BottleneckBlock, [3, 4, 6, 3], **kw)


def resnet101(pretrained=False, **kw):
    return ResNet(BottleneckBlock, [3, 4, 23, 3], **kw)


from .models_ext import *  # noqa: E402,F401,F403
from .models_ext import __all__ as _ext_all
__all__ = list(__all__) + list(_ext_all)


def vgg11(pretrained=False, batch_norm=False, **kwargs):
    return VGG(_vgg_layers([64, "M", 128, "M", 256, 256, "M", 512, 512,
                            "M", 512, 512, "M"], batch_norm), **kwargs)


def vgg13(pretrained=False, batch_norm=False, **kwargs):
    return VGG(_vgg_layers([64, 64, "M", 128, 128, "M", 256, 256, "M",
                            512, 512, "M", 512, 512, "M"], batch_norm),
               **kwargs)


def vgg19(pretrained=False, batch_norm=False, **kwargs):
    return VGG(_vgg_layers([64, 64, "M", 128, 128, "M", 256, 256, 256, 256,
                            "M", 512, 512, 512, 512, "M", 512, 512, 512,
                            512, "M"], batch_norm), **kwargs)


def resnet152(pretrained=False, **kw):
    return ResNet(BottleneckBlock, [3, 8, 36, 3], **kw)


def resnext50_32x4d(pretrained=False, **kw):
    return ResNet(BottleneckBlock, [3, 4, 6, 3], groups=32,
                  width_per_group=4, **kw)


def resnext101_32x4d(pretrained=False, **kw):
    return ResNet(BottleneckBlock, [3, 4, 23, 3], groups=32,
                  width_per_group=4, **kw)


def resnext50_64x4d(pretrained=False, **kw):
    return ResNet(BottleneckBlock, [3, 4, 6, 3], groups=64,
                  width_per_group=4, **kw)


def resnext101_64x4d(pretrained=False, **kw):
    return ResNet(BottleneckBlock, [3, 4, 23, 3], groups=64,
                  width_per_group=4, **kw)


def resnext152_32x4d(pretrained=False, **kw):
    return ResNet(BottleneckBlock, [3, 8, 36, 3], groups=32,
                  width_per_group=4, **kw)


def resnext152_64x4d(pretrained=False, **kw):
    return ResNet(BottleneckBlock, [3, 8, 36, 3], groups=64,
                  width_per_group=4, **kw)


def wide_resnet50_2(pretrained=False, **kw):
    return ResNet(BottleneckBlock, [3, 4, 6, 3], width_per_group=128, **kw)


def wide_resnet101_2(pretrained=False, **kw):
    return ResNet(BottleneckBlock, [3, 4, 23, 3], width_per_group=128, **kw)


__all__ += ["vgg11", "vgg13", "vgg19", "resnet152", "resnext50_32x4d",
            "resnext101_32x4d", "resnext50_64x4d", "resnext101_64x4d",
            "resnext152_32x4d", "resnext152_64x4d", "wide_resnet50_2",
            "wide_resnet101_2"]
