"""Vision model zoo extension: the rest of paddle.vision.models.

Reference: python/paddle/vision/models/{alexnet,mobilenetv1,mobilenetv2,
mobilenetv3,squeezenet,densenet,googlenet,inceptionv3,shufflenetv2}.py.
Architectures match the reference configs; NCHW layout; XLA tiles convs
onto the MXU.
"""
from __future__ import annotations

import paddle_tpu.nn as nn

__all__ = [
    "AlexNet", "alexnet", "MobileNetV1", "mobilenet_v1", "MobileNetV2",
    "mobilenet_v2", "MobileNetV3Small", "MobileNetV3Large",
    "mobilenet_v3_small", "mobilenet_v3_large", "SqueezeNet",
    "squeezenet1_0", "squeezenet1_1", "DenseNet", "densenet121",
    "densenet161", "densenet169", "densenet201", "GoogLeNet", "googlenet",
    "InceptionV3", "inception_v3", "ShuffleNetV2", "shufflenet_v2_x1_0",
    "shufflenet_v2_x0_25", "shufflenet_v2_x0_33", "shufflenet_v2_x0_5",
    "shufflenet_v2_x1_5", "shufflenet_v2_x2_0", "shufflenet_v2_swish",
    "densenet264",
]


def _conv_bn(c_in, c_out, k, stride=1, padding=0, groups=1, act="relu"):
    layers = [nn.Conv2D(c_in, c_out, k, stride=stride, padding=padding,
                        groups=groups, bias_attr=False),
              nn.BatchNorm2D(c_out)]
    if act == "relu":
        layers.append(nn.ReLU())
    elif act == "relu6":
        layers.append(nn.ReLU6())
    elif act == "hardswish":
        layers.append(nn.Hardswish())
    elif act == "swish":
        layers.append(nn.SiLU())
    return nn.Sequential(*layers)


# ------------------------------------------------------------- AlexNet

class AlexNet(nn.Layer):
    """reference vision/models/alexnet.py"""

    def __init__(self, num_classes=1000):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2))
        self.avgpool = nn.AdaptiveAvgPool2D((6, 6))
        self.classifier = nn.Sequential(
            nn.Dropout(), nn.Linear(256 * 36, 4096), nn.ReLU(),
            nn.Dropout(), nn.Linear(4096, 4096), nn.ReLU(),
            nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        return self.classifier(x.flatten(1))


def alexnet(pretrained=False, **kw):
    return AlexNet(**kw)


# --------------------------------------------------------- MobileNetV1

class MobileNetV1(nn.Layer):
    """Depthwise-separable stack (reference mobilenetv1.py)."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        s = lambda c: max(8, int(c * scale))
        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
              [(512, 1024, 2), (1024, 1024, 1)]
        layers = [_conv_bn(3, s(32), 3, stride=2, padding=1)]
        for c_in, c_out, stride in cfg:
            layers.append(_conv_bn(s(c_in), s(c_in), 3, stride=stride,
                                   padding=1, groups=s(c_in)))  # depthwise
            layers.append(_conv_bn(s(c_in), s(c_out), 1))       # pointwise
        self.features = nn.Sequential(*layers)
        self.with_pool = with_pool
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc = nn.Linear(s(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        return self.fc(x.flatten(1))


def mobilenet_v1(pretrained=False, scale=1.0, **kw):
    return MobileNetV1(scale=scale, **kw)


# --------------------------------------------------------- MobileNetV2

class _InvertedResidual(nn.Layer):
    def __init__(self, c_in, c_out, stride, expand):
        super().__init__()
        hidden = int(round(c_in * expand))
        self.use_res = stride == 1 and c_in == c_out
        layers = []
        if expand != 1:
            layers.append(_conv_bn(c_in, hidden, 1, act="relu6"))
        layers += [
            _conv_bn(hidden, hidden, 3, stride=stride, padding=1,
                     groups=hidden, act="relu6"),
            _conv_bn(hidden, c_out, 1, act=None),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    """reference mobilenetv2.py (t,c,n,s table)."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        table = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2),
                 (6, 64, 4, 2), (6, 96, 3, 1), (6, 160, 3, 2),
                 (6, 320, 1, 1)]
        s = lambda c: max(8, int(c * scale))
        c_in = s(32)
        layers = [_conv_bn(3, c_in, 3, stride=2, padding=1, act="relu6")]
        for t, c, n, stride in table:
            for i in range(n):
                layers.append(_InvertedResidual(
                    c_in, s(c), stride if i == 0 else 1, t))
                c_in = s(c)
        last = max(1280, int(1280 * scale))
        layers.append(_conv_bn(c_in, last, 1, act="relu6"))
        self.features = nn.Sequential(*layers)
        self.with_pool = with_pool
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        self.classifier = nn.Sequential(nn.Dropout(0.2),
                                        nn.Linear(last, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        return self.classifier(x.flatten(1))


def mobilenet_v2(pretrained=False, scale=1.0, **kw):
    return MobileNetV2(scale=scale, **kw)


# --------------------------------------------------------- MobileNetV3

class _SE(nn.Layer):
    def __init__(self, c, r=4):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(c, c // r, 1)
        self.fc2 = nn.Conv2D(c // r, c, 1)
        self.relu = nn.ReLU()
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _MBV3Block(nn.Layer):
    def __init__(self, c_in, hidden, c_out, k, stride, se, act):
        super().__init__()
        self.use_res = stride == 1 and c_in == c_out
        layers = []
        if hidden != c_in:
            layers.append(_conv_bn(c_in, hidden, 1, act=act))
        layers.append(_conv_bn(hidden, hidden, k, stride=stride,
                               padding=k // 2, groups=hidden, act=act))
        if se:
            layers.append(_SE(hidden))
        layers.append(_conv_bn(hidden, c_out, 1, act=None))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


_MBV3_LARGE = [
    # k, hidden, out, se, act, stride
    (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2), (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1), (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1), (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2), (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]
_MBV3_SMALL = [
    (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1), (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1), (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2), (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_c, num_classes=1000, scale=1.0,
                 with_pool=True):
        super().__init__()
        s = lambda c: max(8, int(c * scale))
        layers = [_conv_bn(3, s(16), 3, stride=2, padding=1,
                           act="hardswish")]
        c_in = s(16)
        for k, hidden, out, se, act, stride in cfg:
            layers.append(_MBV3Block(c_in, s(hidden), s(out), k, stride,
                                     se, act))
            c_in = s(out)
        last_hidden = s(cfg[-1][1])
        layers.append(_conv_bn(c_in, last_hidden, 1, act="hardswish"))
        self.features = nn.Sequential(*layers)
        self.with_pool = with_pool
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        self.classifier = nn.Sequential(
            nn.Linear(last_hidden, last_c), nn.Hardswish(),
            nn.Dropout(0.2), nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        return self.classifier(x.flatten(1))


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_MBV3_LARGE, 1280, num_classes, scale, with_pool)


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_MBV3_SMALL, 1024, num_classes, scale, with_pool)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kw):
    return MobileNetV3Large(scale=scale, **kw)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kw):
    return MobileNetV3Small(scale=scale, **kw)


# ---------------------------------------------------------- SqueezeNet

class _Fire(nn.Layer):
    def __init__(self, c_in, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Sequential(nn.Conv2D(c_in, squeeze, 1), nn.ReLU())
        self.expand1 = nn.Sequential(nn.Conv2D(squeeze, e1, 1), nn.ReLU())
        self.expand3 = nn.Sequential(nn.Conv2D(squeeze, e3, 3, padding=1),
                                     nn.ReLU())

    def forward(self, x):
        import paddle_tpu as pt
        s = self.squeeze(x)
        return pt.concat([self.expand1(s), self.expand3(s)], axis=1)


class SqueezeNet(nn.Layer):
    """reference squeezenet.py (versions 1.0/1.1)."""

    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, 2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128), nn.MaxPool2D(3, 2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, 2), _Fire(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, 2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, 2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, 2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        self.classifier = nn.Sequential(
            nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU(),
            nn.AdaptiveAvgPool2D(1))

    def forward(self, x):
        return self.classifier(self.features(x)).flatten(1)


def squeezenet1_0(pretrained=False, **kw):
    return SqueezeNet("1.0", **kw)


def squeezenet1_1(pretrained=False, **kw):
    return SqueezeNet("1.1", **kw)


# ------------------------------------------------------------ DenseNet

class _DenseLayer(nn.Layer):
    def __init__(self, c_in, growth, bn_size):
        super().__init__()
        self.fn = nn.Sequential(
            nn.BatchNorm2D(c_in), nn.ReLU(),
            nn.Conv2D(c_in, bn_size * growth, 1, bias_attr=False),
            nn.BatchNorm2D(bn_size * growth), nn.ReLU(),
            nn.Conv2D(bn_size * growth, growth, 3, padding=1,
                      bias_attr=False))

    def forward(self, x):
        import paddle_tpu as pt
        return pt.concat([x, self.fn(x)], axis=1)


class DenseNet(nn.Layer):
    """reference densenet.py."""

    _cfgs = {121: (64, 32, (6, 12, 24, 16)),
             161: (96, 48, (6, 12, 36, 24)),
             169: (64, 32, (6, 12, 32, 32)),
             201: (64, 32, (6, 12, 48, 32)),
             264: (64, 32, (6, 12, 64, 48))}

    def __init__(self, layers=121, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        init_c, growth, blocks = self._cfgs[layers]
        feats = [nn.Conv2D(3, init_c, 7, stride=2, padding=3,
                           bias_attr=False),
                 nn.BatchNorm2D(init_c), nn.ReLU(),
                 nn.MaxPool2D(3, 2, padding=1)]
        c = init_c
        for bi, n in enumerate(blocks):
            for _ in range(n):
                feats.append(_DenseLayer(c, growth, bn_size))
                c += growth
            if bi != len(blocks) - 1:  # transition
                feats += [nn.BatchNorm2D(c), nn.ReLU(),
                          nn.Conv2D(c, c // 2, 1, bias_attr=False),
                          nn.AvgPool2D(2, 2)]
                c //= 2
        feats += [nn.BatchNorm2D(c), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        self.with_pool = with_pool
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        self.classifier = nn.Linear(c, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        return self.classifier(x.flatten(1))


def densenet121(pretrained=False, **kw):
    return DenseNet(121, **kw)


def densenet161(pretrained=False, **kw):
    return DenseNet(161, **kw)


def densenet169(pretrained=False, **kw):
    return DenseNet(169, **kw)


def densenet264(pretrained=False, **kw):
    return DenseNet(layers=264, **kw)


def densenet201(pretrained=False, **kw):
    return DenseNet(201, **kw)


# ----------------------------------------------------------- GoogLeNet

class _Inception(nn.Layer):
    def __init__(self, c_in, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = _conv_bn(c_in, c1, 1)
        self.b2 = nn.Sequential(_conv_bn(c_in, c3r, 1),
                                _conv_bn(c3r, c3, 3, padding=1))
        self.b3 = nn.Sequential(_conv_bn(c_in, c5r, 1),
                                _conv_bn(c5r, c5, 5, padding=2))
        self.b4 = nn.Sequential(nn.MaxPool2D(3, 1, padding=1),
                                _conv_bn(c_in, proj, 1))

    def forward(self, x):
        import paddle_tpu as pt
        return pt.concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                         axis=1)


class GoogLeNet(nn.Layer):
    """reference googlenet.py (main head only at inference; aux heads
    returned in training mode like the reference)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = nn.Sequential(
            _conv_bn(3, 64, 7, stride=2, padding=3), nn.MaxPool2D(3, 2),
            _conv_bn(64, 64, 1), _conv_bn(64, 192, 3, padding=1),
            nn.MaxPool2D(3, 2))
        self.i3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, 2)
        self.i4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, 2)
        self.i5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        self.pool5 = nn.AdaptiveAvgPool2D(1)
        self.dropout = nn.Dropout(0.4)
        self.fc = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.i3b(self.i3a(x)))
        x = self.i4e(self.i4d(self.i4c(self.i4b(self.i4a(x)))))
        x = self.pool4(x)
        x = self.i5b(self.i5a(x))
        x = self.dropout(self.pool5(x).flatten(1))
        return self.fc(x)


def googlenet(pretrained=False, **kw):
    return GoogLeNet(**kw)


# ---------------------------------------------------------- InceptionV3

class InceptionV3(nn.Layer):
    """Compact InceptionV3 (reference inceptionv3.py topology: stem +
    InceptionA/B/C/D/E stacks)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        cb = _conv_bn
        self.stem = nn.Sequential(
            cb(3, 32, 3, stride=2), cb(32, 32, 3), cb(32, 64, 3, padding=1),
            nn.MaxPool2D(3, 2), cb(64, 80, 1), cb(80, 192, 3),
            nn.MaxPool2D(3, 2))

        def block_a(c_in, pool_c):
            return _Inception(c_in, 64, 48, 64, 64, 96, pool_c)

        self.a1 = block_a(192, 32)
        self.a2 = block_a(256, 64)
        self.a3 = block_a(288, 64)
        self.red1 = nn.Sequential(cb(288, 384, 3, stride=2))
        self.red1_pool = nn.MaxPool2D(3, 2)
        c = 384 + 288
        self.b1 = _Inception(c, 192, 128, 192, 128, 192, 96)
        cb2 = 192 * 3 + 96
        self.red2 = nn.Sequential(cb(cb2, 320, 3, stride=2))
        self.red2_pool = nn.MaxPool2D(3, 2)
        c3 = 320 + cb2
        self.c1 = _Inception(c3, 320, 384, 384, 448, 384, 192)
        final_c = 320 + 384 + 384 + 192
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.dropout = nn.Dropout(0.5)
        self.fc = nn.Linear(final_c, num_classes)

    def forward(self, x):
        import paddle_tpu as pt
        x = self.stem(x)
        x = self.a3(self.a2(self.a1(x)))
        x = pt.concat([self.red1(x), self.red1_pool(x)], axis=1)
        x = self.b1(x)
        x = pt.concat([self.red2(x), self.red2_pool(x)], axis=1)
        x = self.c1(x)
        x = self.dropout(self.pool(x).flatten(1))
        return self.fc(x)


def inception_v3(pretrained=False, **kw):
    return InceptionV3(**kw)


# --------------------------------------------------------- ShuffleNetV2

class _ShuffleUnit(nn.Layer):
    def __init__(self, c_in, c_out, stride):
        super().__init__()
        self.stride = stride
        branch_c = c_out // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                _conv_bn(branch_c, branch_c, 1),
                _conv_bn(branch_c, branch_c, 3, stride=1, padding=1,
                         groups=branch_c, act=None),
                _conv_bn(branch_c, branch_c, 1))
            self.branch1 = None
        else:
            self.branch1 = nn.Sequential(
                _conv_bn(c_in, c_in, 3, stride=stride, padding=1,
                         groups=c_in, act=None),
                _conv_bn(c_in, branch_c, 1))
            self.branch2 = nn.Sequential(
                _conv_bn(c_in, branch_c, 1),
                _conv_bn(branch_c, branch_c, 3, stride=stride, padding=1,
                         groups=branch_c, act=None),
                _conv_bn(branch_c, branch_c, 1))

    def forward(self, x):
        import paddle_tpu as pt
        if self.stride == 1:
            half = x.shape[1] // 2
            x1 = x[:, :half]
            x2 = x[:, half:]
            out = pt.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = pt.concat([self.branch1(x), self.branch2(x)], axis=1)
        # channel shuffle (2 groups)
        b, c = out.shape[0], out.shape[1]
        h, w = out.shape[2], out.shape[3]
        out = out.reshape([b, 2, c // 2, h, w]).transpose(
            [0, 2, 1, 3, 4]).reshape([b, c, h, w])
        return out


class ShuffleNetV2(nn.Layer):
    """reference shufflenetv2.py (x1.0 config default)."""

    _stage_c = {0.25: (24, 48, 96, 512), 0.33: (32, 64, 128, 512),
                0.5: (48, 96, 192, 1024), 1.0: (116, 232, 464, 1024),
                1.5: (176, 352, 704, 1024), 2.0: (244, 488, 976, 2048)}

    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        c1, c2, c3, c_last = self._stage_c[scale]
        self.stem = nn.Sequential(_conv_bn(3, 24, 3, stride=2, padding=1),
                                  nn.MaxPool2D(3, 2, padding=1))
        stages = []
        c_in = 24
        for c_out, repeat in ((c1, 4), (c2, 8), (c3, 4)):
            stages.append(_ShuffleUnit(c_in, c_out, 2))
            for _ in range(repeat - 1):
                stages.append(_ShuffleUnit(c_out, c_out, 1))
            c_in = c_out
        self.stages = nn.Sequential(*stages)
        self.last = _conv_bn(c3, c_last, 1)
        self.with_pool = with_pool
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc = nn.Linear(c_last, num_classes)

    def forward(self, x):
        x = self.last(self.stages(self.stem(x)))
        if self.with_pool:
            x = self.pool(x)
        return self.fc(x.flatten(1))


def shufflenet_v2_x0_25(pretrained=False, **kw):
    return ShuffleNetV2(scale=0.25, **kw)


def shufflenet_v2_x0_33(pretrained=False, **kw):
    return ShuffleNetV2(scale=0.33, **kw)


def shufflenet_v2_x0_5(pretrained=False, **kw):
    return ShuffleNetV2(scale=0.5, **kw)


def shufflenet_v2_x1_0(pretrained=False, **kw):
    return ShuffleNetV2(scale=1.0, **kw)


def shufflenet_v2_x1_5(pretrained=False, **kw):
    return ShuffleNetV2(scale=1.5, **kw)


def shufflenet_v2_x2_0(pretrained=False, **kw):
    return ShuffleNetV2(scale=2.0, **kw)


def shufflenet_v2_swish(pretrained=False, **kw):
    kw.setdefault("act", "swish")
    return ShuffleNetV2(scale=1.0, **kw)
