"""paddle.vision.transforms parity (numpy host-side pipeline).

Reference: python/paddle/vision/transforms/. Host-side image preprocessing
stays numpy (feeding device_put once per batch); geometric ops use jax.image
when run on device tensors.
"""
from __future__ import annotations

import numbers

import numpy as np

from ..core.tensor import Tensor, wrap

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "BrightnessTransform", "ContrastTransform", "Pad",
           "RandomResizedCrop", "to_tensor", "normalize", "resize",
           "hflip", "vflip", "center_crop", "crop", "pad"]


def _chw(img):
    a = np.asarray(img)
    if a.ndim == 2:
        a = a[None]
    elif a.ndim == 3 and a.shape[-1] in (1, 3, 4):
        a = a.transpose(2, 0, 1)
    return a


def to_tensor(img, data_format="CHW"):
    a = np.asarray(img).astype(np.float32)
    if a.max() > 1.5:
        a = a / 255.0
    if data_format == "CHW":
        a = _chw(a)
    return wrap(__import__("jax.numpy", fromlist=["asarray"]).asarray(a))


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    a = img.numpy() if isinstance(img, Tensor) else np.asarray(img,
                                                              np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        mean = mean.reshape(-1, 1, 1)
        std = std.reshape(-1, 1, 1)
    out = (a - mean) / std
    if isinstance(img, Tensor):
        import jax.numpy as jnp
        return wrap(jnp.asarray(out))
    return out


def resize(img, size, interpolation="bilinear"):
    a = np.asarray(img)
    import jax
    import jax.numpy as jnp
    if isinstance(size, int):
        h, w = a.shape[0], a.shape[1]
        if h < w:
            size = (size, int(size * w / h))
        else:
            size = (int(size * h / w), size)
    method = {"nearest": "nearest", "bilinear": "linear",
              "bicubic": "cubic"}[interpolation]
    out_shape = tuple(size) + a.shape[2:]
    return np.asarray(jax.image.resize(jnp.asarray(a, jnp.float32),
                                       out_shape, method=method))


def hflip(img):
    return np.asarray(img)[:, ::-1].copy()


def vflip(img):
    return np.asarray(img)[::-1].copy()


def crop(img, top, left, height, width):
    return np.asarray(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    a = np.asarray(img)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    h, w = a.shape[0], a.shape[1]
    th, tw = output_size
    return crop(a, (h - th) // 2, (w - tw) // 2, th, tw)


def pad(img, padding, fill=0, padding_mode="constant"):
    a = np.asarray(img)
    if isinstance(padding, int):
        padding = (padding,) * 4
    left, top, right, bottom = padding if len(padding) == 4 else \
        (padding[0], padding[1], padding[0], padding[1])
    width = [(top, bottom), (left, right)] + [(0, 0)] * (a.ndim - 2)
    if padding_mode == "constant":
        return np.pad(a, width, constant_values=fill)
    return np.pad(a, width, mode=padding_mode)


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = mean
        self.std = std
        self.data_format = data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        self.size = (size, size) if isinstance(size, int) else size
        self.padding = padding

    def _apply_image(self, img):
        a = np.asarray(img)
        if self.padding:
            a = pad(a, self.padding)
        h, w = a.shape[0], a.shape[1]
        th, tw = self.size
        top = np.random.randint(0, max(h - th, 0) + 1)
        left = np.random.randint(0, max(w - tw, 0) + 1)
        return crop(a, top, left, th, tw)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        a = np.asarray(img)
        h, w = a.shape[0], a.shape[1]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if cw <= w and ch <= h:
                top = np.random.randint(0, h - ch + 1)
                left = np.random.randint(0, w - cw + 1)
                return resize(crop(a, top, left, ch, cw), self.size,
                              self.interpolation)
        return resize(center_crop(a, min(h, w)), self.size,
                      self.interpolation)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return hflip(img)
        return np.asarray(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return vflip(img)
        return np.asarray(img)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        return np.asarray(img).transpose(self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        a = np.asarray(img, np.float32)
        factor = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return np.clip(a * factor, 0, 255 if a.max() > 1.5 else 1.0)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        a = np.asarray(img, np.float32)
        factor = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        mean = a.mean()
        return np.clip((a - mean) * factor + mean,
                       0, 255 if a.max() > 1.5 else 1.0)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


# ------------------------------------------------- round-3 transform tail
# (reference python/paddle/vision/transforms/{transforms,functional}.py)
# Host-side preprocessing is numpy by design (the device step starts at
# ToTensor); images are HWC (or HW) arrays as from the cv2 backend.


def _hwc(img):
    a = np.asarray(img)
    return a[:, :, None] if a.ndim == 2 else a


def _clip_like(a, ref):
    return np.clip(a, 0, 255.0 if np.asarray(ref).max() > 1.5 else 1.0)


def to_grayscale(img, num_output_channels=1):
    a = _hwc(img).astype(np.float32)
    gray = a[..., 0] * 0.299 + a[..., 1] * 0.587 + a[..., 2] * 0.114 \
        if a.shape[-1] >= 3 else a[..., 0]
    out = np.repeat(gray[..., None], num_output_channels, -1)
    return out.astype(np.asarray(img).dtype)


def adjust_brightness(img, brightness_factor):
    a = _hwc(img).astype(np.float32)
    return _clip_like(a * brightness_factor, img).astype(np.float32)


def adjust_contrast(img, contrast_factor):
    a = _hwc(img).astype(np.float32)
    mean = to_grayscale(a).mean()
    return _clip_like((a - mean) * contrast_factor + mean,
                      img).astype(np.float32)


def adjust_saturation(img, saturation_factor):
    a = _hwc(img).astype(np.float32)
    gray = to_grayscale(a, 3).astype(np.float32)
    return _clip_like(gray + saturation_factor * (a - gray),
                      img).astype(np.float32)


def adjust_hue(img, hue_factor):
    """Shift hue by hue_factor (in [-0.5, 0.5]) via vectorized RGB<->HSV."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    a = _hwc(img).astype(np.float32)
    scale = 255.0 if np.asarray(img).max() > 1.5 else 1.0
    rgb = a[..., :3] / scale
    mx, mn = rgb.max(-1), rgb.min(-1)
    d = mx - mn + 1e-12
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    h = np.where(mx == r, ((g - b) / d) % 6,
                 np.where(mx == g, (b - r) / d + 2, (r - g) / d + 4)) / 6.0
    s = np.where(mx > 0, d / (mx + 1e-12), 0.0)
    v = mx
    h = (h + hue_factor) % 1.0
    i = np.floor(h * 6).astype(np.int32)
    f = h * 6 - i
    p, q, t = v * (1 - s), v * (1 - f * s), v * (1 - (1 - f) * s)
    i = i % 6
    out = np.choose(i[..., None],
                    [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
                     np.stack([p, v, t], -1), np.stack([p, q, v], -1),
                     np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
    return (out * scale).astype(np.float32)


def _warp(img, inv_matrix, fill=0.0):
    """Inverse-map bilinear warp: out(x) = img(M @ x) for 3x3 M."""
    a = _hwc(img).astype(np.float32)
    h, w = a.shape[:2]
    ys, xs = np.mgrid[0:h, 0:w].astype(np.float32)
    ones = np.ones_like(xs)
    coords = np.stack([xs, ys, ones], 0).reshape(3, -1)
    src = inv_matrix @ coords
    sx = src[0] / src[2]
    sy = src[1] / src[2]
    x0, y0 = np.floor(sx), np.floor(sy)
    dx, dy = sx - x0, sy - y0

    def at(ix, iy):
        inb = (ix >= 0) & (ix < w) & (iy >= 0) & (iy < h)
        v = a[np.clip(iy, 0, h - 1).astype(int),
              np.clip(ix, 0, w - 1).astype(int)]
        return np.where(inb[:, None], v, fill)

    out = (at(x0, y0) * ((1 - dx) * (1 - dy))[:, None]
           + at(x0 + 1, y0) * (dx * (1 - dy))[:, None]
           + at(x0, y0 + 1) * ((1 - dx) * dy)[:, None]
           + at(x0 + 1, y0 + 1) * (dx * dy)[:, None])
    return out.reshape(h, w, a.shape[-1]).astype(np.float32)


def _affine_matrix(angle, translate, scale, shear, center):
    rot = np.deg2rad(angle)
    sx, sy = np.deg2rad(shear[0]), np.deg2rad(shear[1])
    cx, cy = center
    tx, ty = translate
    # forward: T(center) R S Sh T(-center) + translate
    rs = np.array([
        [np.cos(rot + sy) / np.cos(sy),
         -np.cos(rot + sy) * np.tan(sx) / np.cos(sy) - np.sin(rot), 0],
        [np.sin(rot + sy) / np.cos(sy),
         -np.sin(rot + sy) * np.tan(sx) / np.cos(sy) + np.cos(rot), 0],
        [0, 0, 1]], np.float32) * scale
    rs[2, 2] = 1.0
    pre = np.array([[1, 0, cx + tx], [0, 1, cy + ty], [0, 0, 1]], np.float32)
    post = np.array([[1, 0, -cx], [0, 1, -cy], [0, 0, 1]], np.float32)
    return pre @ rs @ post


def affine(img, angle=0.0, translate=(0, 0), scale=1.0, shear=(0.0, 0.0),
           interpolation="bilinear", fill=0, center=None):
    a = _hwc(img)
    h, w = a.shape[:2]
    if np.isscalar(shear):
        shear = (float(shear), 0.0)
    center = center or ((w - 1) * 0.5, (h - 1) * 0.5)
    m = _affine_matrix(angle, translate, scale, shear, center)
    return _warp(a, np.linalg.inv(m), fill)


def rotate(img, angle, interpolation="bilinear", expand=False, center=None,
           fill=0):
    return affine(img, angle=angle, center=center, fill=fill)


def _homography(src_pts, dst_pts):
    A = []
    for (x, y), (u, v) in zip(src_pts, dst_pts):
        A.append([x, y, 1, 0, 0, 0, -u * x, -u * y])
        A.append([0, 0, 0, x, y, 1, -v * x, -v * y])
    b = np.array([c for p in dst_pts for c in p], np.float32)
    h8 = np.linalg.lstsq(np.array(A, np.float32), b, rcond=None)[0]
    return np.append(h8, 1.0).reshape(3, 3)


def perspective(img, startpoints, endpoints, interpolation="bilinear",
                fill=0):
    m = _homography(startpoints, endpoints)   # maps start -> end
    return _warp(img, np.linalg.inv(m), fill)


def erase(img, i, j, h, w, v, inplace=False):
    """Erase region [i:i+h, j:j+w] with value v (Tensor/ndarray, CHW or
    HWC both handled: CHW for Tensors per reference)."""
    from ..core.tensor import Tensor
    if isinstance(img, Tensor):
        a = img.numpy().copy()
        a[..., i:i + h, j:j + w] = v
        import paddle_tpu as pt
        return pt.to_tensor(a)
    a = np.asarray(img).copy()
    if a.ndim == 3 and a.shape[-1] in (1, 3, 4):   # HWC
        a[i:i + h, j:j + w] = v
    else:
        a[..., i:i + h, j:j + w] = v
    return a


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        f = np.random.uniform(-self.value, self.value)
        return adjust_hue(img, f)


class ColorJitter(BaseTransform):
    """Randomly order and apply brightness/contrast/saturation/hue."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self.transforms = []
        if brightness:
            self.transforms.append(BrightnessTransform(brightness))
        if contrast:
            self.transforms.append(ContrastTransform(contrast))
        if saturation:
            self.transforms.append(SaturationTransform(saturation))
        if hue:
            self.transforms.append(HueTransform(hue))

    def _apply_image(self, img):
        for t in np.random.permutation(len(self.transforms)):
            img = self.transforms[int(t)]._apply_image(img)
        return img


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="bilinear", expand=False,
                 center=None, fill=0, keys=None):
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) \
            else tuple(degrees)
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = np.random.uniform(*self.degrees)
        return rotate(img, angle, center=self.center, fill=self.fill)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="bilinear", fill=0, center=None, keys=None):
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) \
            else tuple(degrees)
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        a = _hwc(img)
        h, w = a.shape[:2]
        angle = np.random.uniform(*self.degrees)
        tx = ty = 0
        if self.translate:
            tx = np.random.uniform(-self.translate[0], self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1], self.translate[1]) * h
        sc = np.random.uniform(*self.scale) if self.scale else 1.0
        if self.shear is None:
            sh = 0.0
        elif np.isscalar(self.shear):
            sh = np.random.uniform(-self.shear, self.shear)
        else:
            sh = np.random.uniform(self.shear[0], self.shear[1])
        return affine(a, angle, (tx, ty), sc, (sh, 0.0), fill=self.fill,
                      center=self.center)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="bilinear", fill=0, keys=None):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.fill = fill

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        a = _hwc(img)
        h, w = a.shape[:2]
        d = self.distortion_scale
        dw, dh = int(d * w // 2), int(d * h // 2)
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [(np.random.randint(0, dw + 1), np.random.randint(0, dh + 1)),
               (w - 1 - np.random.randint(0, dw + 1),
                np.random.randint(0, dh + 1)),
               (w - 1 - np.random.randint(0, dw + 1),
                h - 1 - np.random.randint(0, dh + 1)),
               (np.random.randint(0, dw + 1),
                h - 1 - np.random.randint(0, dh + 1))]
        return perspective(a, start, end, fill=self.fill)


class RandomErasing(BaseTransform):
    """Reference RandomErasing (cutout-style); operates on HWC/CHW arrays."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        a = _hwc(np.asarray(img))
        h, w = a.shape[:2]
        area = h * w
        for _ in range(10):
            target = np.random.uniform(*self.scale) * area
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh + 1)
                j = np.random.randint(0, w - ew + 1)
                return erase(a, i, j, eh, ew, self.value)
        return a


__all__ += ["SaturationTransform", "HueTransform", "ColorJitter",
            "Grayscale", "RandomRotation", "RandomAffine",
            "RandomPerspective", "RandomErasing", "to_grayscale",
            "adjust_brightness", "adjust_contrast", "adjust_saturation",
            "adjust_hue", "affine", "rotate", "perspective", "erase"]
