"""paddle.vision.transforms parity (numpy host-side pipeline).

Reference: python/paddle/vision/transforms/. Host-side image preprocessing
stays numpy (feeding device_put once per batch); geometric ops use jax.image
when run on device tensors.
"""
from __future__ import annotations

import numbers

import numpy as np

from ..core.tensor import Tensor, wrap

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "BrightnessTransform", "ContrastTransform", "Pad",
           "RandomResizedCrop", "to_tensor", "normalize", "resize",
           "hflip", "vflip", "center_crop", "crop", "pad"]


def _chw(img):
    a = np.asarray(img)
    if a.ndim == 2:
        a = a[None]
    elif a.ndim == 3 and a.shape[-1] in (1, 3, 4):
        a = a.transpose(2, 0, 1)
    return a


def to_tensor(img, data_format="CHW"):
    a = np.asarray(img).astype(np.float32)
    if a.max() > 1.5:
        a = a / 255.0
    if data_format == "CHW":
        a = _chw(a)
    return wrap(__import__("jax.numpy", fromlist=["asarray"]).asarray(a))


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    a = img.numpy() if isinstance(img, Tensor) else np.asarray(img,
                                                              np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        mean = mean.reshape(-1, 1, 1)
        std = std.reshape(-1, 1, 1)
    out = (a - mean) / std
    if isinstance(img, Tensor):
        import jax.numpy as jnp
        return wrap(jnp.asarray(out))
    return out


def resize(img, size, interpolation="bilinear"):
    a = np.asarray(img)
    import jax
    import jax.numpy as jnp
    if isinstance(size, int):
        h, w = a.shape[0], a.shape[1]
        if h < w:
            size = (size, int(size * w / h))
        else:
            size = (int(size * h / w), size)
    method = {"nearest": "nearest", "bilinear": "linear",
              "bicubic": "cubic"}[interpolation]
    out_shape = tuple(size) + a.shape[2:]
    return np.asarray(jax.image.resize(jnp.asarray(a, jnp.float32),
                                       out_shape, method=method))


def hflip(img):
    return np.asarray(img)[:, ::-1].copy()


def vflip(img):
    return np.asarray(img)[::-1].copy()


def crop(img, top, left, height, width):
    return np.asarray(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    a = np.asarray(img)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    h, w = a.shape[0], a.shape[1]
    th, tw = output_size
    return crop(a, (h - th) // 2, (w - tw) // 2, th, tw)


def pad(img, padding, fill=0, padding_mode="constant"):
    a = np.asarray(img)
    if isinstance(padding, int):
        padding = (padding,) * 4
    left, top, right, bottom = padding if len(padding) == 4 else \
        (padding[0], padding[1], padding[0], padding[1])
    width = [(top, bottom), (left, right)] + [(0, 0)] * (a.ndim - 2)
    if padding_mode == "constant":
        return np.pad(a, width, constant_values=fill)
    return np.pad(a, width, mode=padding_mode)


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = mean
        self.std = std
        self.data_format = data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        self.size = (size, size) if isinstance(size, int) else size
        self.padding = padding

    def _apply_image(self, img):
        a = np.asarray(img)
        if self.padding:
            a = pad(a, self.padding)
        h, w = a.shape[0], a.shape[1]
        th, tw = self.size
        top = np.random.randint(0, max(h - th, 0) + 1)
        left = np.random.randint(0, max(w - tw, 0) + 1)
        return crop(a, top, left, th, tw)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        a = np.asarray(img)
        h, w = a.shape[0], a.shape[1]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if cw <= w and ch <= h:
                top = np.random.randint(0, h - ch + 1)
                left = np.random.randint(0, w - cw + 1)
                return resize(crop(a, top, left, ch, cw), self.size,
                              self.interpolation)
        return resize(center_crop(a, min(h, w)), self.size,
                      self.interpolation)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return hflip(img)
        return np.asarray(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return vflip(img)
        return np.asarray(img)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        return np.asarray(img).transpose(self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        a = np.asarray(img, np.float32)
        factor = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return np.clip(a * factor, 0, 255 if a.max() > 1.5 else 1.0)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        a = np.asarray(img, np.float32)
        factor = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        mean = a.mean()
        return np.clip((a - mean) * factor + mean,
                       0, 255 if a.max() > 1.5 else 1.0)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)
