"""Tensor creation ops (paddle.zeros/ones/arange/... parity).

Reference: python/paddle/tensor/creation.py.
"""
import jax.numpy as jnp

from ..core.dtype import convert_dtype
from ..core.tensor import Tensor, unwrap, wrap
from .registry import register_direct


def _mk(value):
    return wrap(value)


def zeros(shape, dtype="float32"):
    return _mk(jnp.zeros(shape, dtype=convert_dtype(dtype)))


def ones(shape, dtype="float32"):
    return _mk(jnp.ones(shape, dtype=convert_dtype(dtype)))


def full(shape, fill_value, dtype="float32"):
    if isinstance(fill_value, Tensor):
        fill_value = unwrap(fill_value)
    return _mk(jnp.full(shape, fill_value, dtype=convert_dtype(dtype)))


def empty(shape, dtype="float32"):
    return _mk(jnp.zeros(shape, dtype=convert_dtype(dtype)))


def zeros_like(x, dtype=None):
    return _mk(jnp.zeros_like(unwrap(x), dtype=convert_dtype(dtype)))


def ones_like(x, dtype=None):
    return _mk(jnp.ones_like(unwrap(x), dtype=convert_dtype(dtype)))


def full_like(x, fill_value, dtype=None):
    return _mk(jnp.full_like(unwrap(x), fill_value, dtype=convert_dtype(dtype)))


def empty_like(x, dtype=None):
    return _mk(jnp.zeros_like(unwrap(x), dtype=convert_dtype(dtype)))


def arange(start=0, end=None, step=1, dtype=None):
    start = unwrap(start) if isinstance(start, Tensor) else start
    end = unwrap(end) if isinstance(end, Tensor) else end
    return _mk(jnp.arange(start, end, step, dtype=convert_dtype(dtype)))


def linspace(start, stop, num, dtype=None):
    return _mk(jnp.linspace(unwrap(start) if isinstance(start, Tensor) else start,
                            unwrap(stop) if isinstance(stop, Tensor) else stop,
                            num, dtype=convert_dtype(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None):
    return _mk(jnp.logspace(start, stop, num, base=base, dtype=convert_dtype(dtype)))


def eye(num_rows, num_columns=None, dtype="float32"):
    return _mk(jnp.eye(num_rows, num_columns, dtype=convert_dtype(dtype)))


def tril_indices(row, col, offset=0):
    return _mk(jnp.stack(jnp.tril_indices(row, offset, col)))


def triu_indices(row, col=None, offset=0):
    return _mk(jnp.stack(jnp.triu_indices(row, offset, col if col else row)))


def clone(x):
    from ..core.tensor import dispatch
    return dispatch(lambda v: v + 0, x, name="clone")


def assign(x, output=None):
    v = unwrap(x) if isinstance(x, Tensor) else jnp.asarray(x)
    if output is not None:
        output._replace_value(jnp.asarray(v))
        return output
    return _mk(jnp.asarray(v))


def complex(real, imag):  # noqa: A001
    from ..core.tensor import dispatch
    import jax.lax as lax
    return dispatch(lax.complex, real, imag, name="complex")


for _n in ["zeros", "ones", "full", "empty", "zeros_like", "ones_like",
           "full_like", "empty_like", "arange", "linspace", "logspace", "eye",
           "tril_indices", "triu_indices", "clone", "assign", "complex"]:
    register_direct(_n, globals()[_n])
