"""Fused linear + softmax cross-entropy (chunked, logits never fully
materialized).

Reference capability: paddle's c_softmax_with_cross_entropy / fused CE
kernels (paddle/fluid/operators/collective/c_softmax_with_cross_entropy_op.cu
and phi softmax_with_cross_entropy); on GPU frameworks the same idea ships
as Liger-style fused-linear-CE. TPU-native design: scan over token chunks —
each chunk's logits ([chunk, V]) live only inside one scan step (MXU matmul
+ fp32 logsumexp), `jax.checkpoint` makes the backward recompute them per
chunk, and the dW accumulation rides the scan's reverse pass. Peak HBM for
the CE drops from O(T*V) fp32 to O(chunk*V).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["fused_linear_cross_entropy"]


def fused_linear_cross_entropy(hidden, weight, labels, chunk_size=1024,
                               reduction="mean", logits_dtype=None):
    """loss = cross_entropy(hidden @ weight, labels) without materializing
    the full [T, V] logits.

    hidden: [T, H] (or [B, S, H] — flattened internally); weight: [H, V];
    labels: int [T] (or [B, S]). The matmul runs in ``hidden.dtype``
    (bf16 on TPU → MXU); softmax statistics are fp32.
    """
    h2 = hidden.reshape(-1, hidden.shape[-1])
    lb = labels.reshape(-1).astype(jnp.int32)
    T = h2.shape[0]
    c = min(chunk_size, T)
    n = T // c

    def chunk_loss(h, l):
        logits = (h @ weight).astype(logits_dtype or jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, l[:, None], axis=-1)[:, 0]
        return jnp.sum(lse - tgt)

    ckpt = jax.checkpoint(chunk_loss)

    def body(carry, hl):
        h, l = hl
        return carry + ckpt(h, l), None

    hs = h2[:n * c].reshape(n, c, h2.shape[-1])
    ls = lb[:n * c].reshape(n, c)
    total, _ = lax.scan(body, jnp.float32(0.0), (hs, ls))
    if T % c != 0:
        # remainder tail keeps the memory win for non-dividing lengths
        total = total + ckpt(h2[n * c:], lb[n * c:])
    if reduction == "mean":
        return total / T
    if reduction == "sum":
        return total
    raise ValueError("chunked CE supports reduction='mean'|'sum'")
