"""Op registry: single source of truth for the generated op namespace.

Reference analogue: paddle/phi/api/yaml/ops.yaml + the api_gen.py /
python_c_gen.py code generators that produce the `_C_ops` namespace
(python/paddle/_C_ops.py). Here an op is a JAX-traceable function registered
once; `make_op` wraps it with the eager dispatch (tape recording) and
`install_tensor_methods` attaches method variants to Tensor — replacing the
reference's generated pybind methods (paddle/fluid/pybind/eager_method.cc).
"""
from __future__ import annotations

import functools

import jax.numpy as jnp

from ..core.tensor import Tensor, dispatch

OPS = {}            # name -> callable (public op)
TENSOR_METHODS = {}  # method name -> callable


def make_op(name, fn, nondiff_args=(), doc=None):
    @functools.wraps(fn)
    def op(*args, **kwargs):
        return dispatch(fn, *args, name=name, nondiff_args=nondiff_args, **kwargs)

    op.__name__ = name
    op.__qualname__ = name
    if doc:
        op.__doc__ = doc
    OPS[name] = op
    return op


def register(name, *, method=None, nondiff_args=()):
    """Decorator form. ``method``: also expose as Tensor method (True→same name)."""

    def deco(fn):
        op = make_op(name, fn, nondiff_args=nondiff_args)
        if method:
            TENSOR_METHODS[name if method is True else method] = op
        return op

    return deco


def register_direct(name, fn, *, method=None):
    """Register an already-dispatching callable (custom control flow inside)."""
    OPS[name] = fn
    if method:
        TENSOR_METHODS[name if method is True else method] = fn
    return fn


# ops that also get an `x.<name>_()` in-place variant (reference: paddle's
# generated *_ inplace APIs, paddle/phi/api/yaml/ops.yaml inplace entries).
# JAX arrays are immutable, so "in-place" = compute + rebind the wrapper's
# value (Tensor.set_value); the recorded tape keeps functional semantics.
INPLACE_OPS = ("add", "subtract", "multiply", "divide", "scale", "clip",
               "exp", "sqrt", "rsqrt", "reciprocal", "floor", "ceil",
               "round", "trunc", "remainder", "lerp", "pow", "tanh",
               "sigmoid", "relu", "squeeze", "unsqueeze", "flatten",
               "flip", "cast", "reshape", "scatter", "index_add",
               "softmax", "elu")


def install_tensor_methods():
    for mname, op in TENSOR_METHODS.items():
        if not hasattr(Tensor, mname):
            setattr(Tensor, mname, op)

    from ..core.tensor import unwrap, wrap

    def mk_inplace(op):
        def method(self, *args, **kwargs):
            # run the op on a SNAPSHOT carrying the pre-mutation tape
            # identity: the new node's parent must be the old value, not
            # the rebound self (self-referential parent would cut the
            # upstream graph out of backward)
            old_node, old_idx = self._node, self._out_index
            snapshot = wrap(unwrap(self),
                            stop_gradient=self.stop_gradient)
            snapshot._node = old_node
            snapshot._out_index = old_idx
            out = op(snapshot, *args, **kwargs)
            # adopt the output tensor wholesale: raw value (cast_/
            # squeeze_ legally change dtype/shape) AND the tape node
            self._value = unwrap(out)
            if isinstance(out, Tensor):
                self._node = out._node
                self._out_index = out._out_index
                self.stop_gradient = out.stop_gradient
                # hooks must survive the inplace rebind and fire on the
                # POST-mutation gradient (paddle semantics: hooks track the
                # tensor, not the node). Two sources: leaf hooks stored on
                # the tensor, and non-leaf hooks on the old node's slot.
                hooks = self._hooks
                self._hooks = None
                if old_node is not None and old_node.out_hooks:
                    moved = old_node.out_hooks.pop(old_idx, None)
                    if moved:
                        # keep list identity where possible so existing
                        # _HookHandles still remove from the live list
                        if hooks:
                            hooks.extend(moved)
                        else:
                            hooks = moved
                if hooks and self._node is not None:
                    if self._node.out_hooks is None:
                        self._node.out_hooks = {}
                    slot = self._node.out_hooks.get(self._out_index)
                    if slot is None:
                        # reuse the list so existing _HookHandles still
                        # remove from the live collection
                        self._node.out_hooks[self._out_index] = hooks
                    else:
                        slot.extend(hooks)
                elif hooks:
                    self._hooks = hooks
            return self
        return method

    for name in INPLACE_OPS:
        op = OPS.get(name)
        if op is not None and not hasattr(Tensor, name + "_"):
            setattr(Tensor, name + "_", mk_inplace(op))

    def zero_(self):
        # constant rebind: detach from the tape (backprop through the old
        # producer would be wrong — the value no longer depends on it)
        self._value = jnp.zeros_like(self._value)
        self._node = None
        self._out_index = 0
        return self

    def fill_(self, value):
        self._value = jnp.full_like(self._value, value)
        self._node = None
        self._out_index = 0
        return self

    if not hasattr(Tensor, "zero_"):
        Tensor.zero_ = zero_
    if not hasattr(Tensor, "fill_"):
        Tensor.fill_ = fill_


# Tensor-method parity tail (reference python/paddle/tensor/__init__.py
# tensor_method_func): ops already registered become methods; a few extra
# in-place random/monkey helpers are defined here.
_EXTRA_TENSOR_METHODS = (
    "cov", "corrcoef", "cond", "lstsq", "dist", "histogram", "bincount",
    "qr", "eigvals", "eigvalsh", "logcumsumexp", "logit", "increment",
    "stanh", "nansum", "nanmean", "count_nonzero", "amax", "amin",
    "fmax", "fmin", "kron", "lgamma", "equal_all", "is_empty",
    "expand_as", "scatter", "scatter_nd_add", "scatter_nd",
    "shard_index", "slice", "vsplit", "tensordot", "strided_slice",
    "unique_consecutive", "unstack", "rot90", "where", "index_sample",
    "digamma", "eig", "multi_dot", "solve", "cholesky_solve",
    "triangular_solve", "lu", "lu_unpack", "as_complex", "as_real",
    "gcd", "lcm", "angle", "take_along_axis", "put_along_axis",
    "heaviside", "index_add", "bucketize",
)


def install_method_tail():
    import jax.numpy as jnp

    for name in _EXTRA_TENSOR_METHODS:
        op = OPS.get(name)
        if op is not None and not hasattr(Tensor, name):
            setattr(Tensor, name, op)

    def broadcast_shape(self, y_shape):
        import paddle_tpu as pt
        return pt.broadcast_shape(list(self.shape), y_shape)

    def broadcast_tensors_m(self, others=None):
        import paddle_tpu as pt
        ts = [self] + list(others or [])
        return pt.broadcast_tensors(ts)

    if not hasattr(Tensor, "broadcast_shape"):
        Tensor.broadcast_shape = broadcast_shape
    if not hasattr(Tensor, "broadcast_tensors"):
        Tensor.broadcast_tensors = broadcast_tensors_m

    for name in ("multiplex", "add_n", "concat", "stack"):
        # these ops take the tensor (or a list) as their first argument;
        # the method form forwards self as that argument
        op = OPS.get(name)
        if op is not None and not hasattr(Tensor, name):
            setattr(Tensor, name, op)

    def floor_mod(self, y):
        return OPS["mod"](self, y)

    def rank(self):
        import paddle_tpu as pt
        return pt.rank(self)

    def is_tensor(self):
        return True

    def is_complex(self):
        return bool(jnp.issubdtype(self._value.dtype, jnp.complexfloating))

    def is_integer(self):
        return bool(jnp.issubdtype(self._value.dtype, jnp.integer))

    def is_floating_point(self):
        return bool(jnp.issubdtype(self._value.dtype, jnp.floating))

    def uniform_(self, min=-1.0, max=1.0, seed=0):  # noqa: A002
        import jax

        from ..core import random as rnd
        self._value = jax.random.uniform(
            rnd.next_key(), self._value.shape,
            self._value.dtype if jnp.issubdtype(self._value.dtype,
                                                jnp.floating)
            else jnp.float32, min, max)
        self._node = None
        return self

    def exponential_(self, lam=1.0):
        import jax

        from ..core import random as rnd
        self._value = (jax.random.exponential(
            rnd.next_key(), self._value.shape) / lam).astype(
                self._value.dtype)
        self._node = None
        return self

    def erfinv_(self):
        return _inp(self, "erfinv")

    def put_along_axis_(self, indices, values, axis, reduce="assign"):  # noqa: A002
        out = OPS["put_along_axis"](self, indices, values, axis, reduce)
        self._value = out._value if isinstance(out, Tensor) else out
        return self

    def _inp(self, opname):
        out = OPS[opname](self)
        self._value = out._value if isinstance(out, Tensor) else out
        return self

    def create_tensor(self, dtype=None):
        import paddle_tpu as pt
        return pt.to_tensor([], dtype=dtype or self.dtype)

    def create_parameter(self, shape, dtype=None, **kw):
        import paddle_tpu as pt
        return pt.create_parameter(shape, dtype or "float32", **kw)

    for name, fn in [("floor_mod", floor_mod), ("rank", rank),
                     ("is_tensor", is_tensor), ("is_complex", is_complex),
                     ("is_integer", is_integer),
                     ("is_floating_point", is_floating_point),
                     ("uniform_", uniform_), ("exponential_", exponential_),
                     ("erfinv_", erfinv_),
                     ("put_along_axis_", put_along_axis_),
                     ("create_tensor", create_tensor),
                     ("create_parameter", create_parameter)]:
        if not hasattr(Tensor, name):
            setattr(Tensor, name, fn)
