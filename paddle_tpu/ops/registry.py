"""Op registry: single source of truth for the generated op namespace.

Reference analogue: paddle/phi/api/yaml/ops.yaml + the api_gen.py /
python_c_gen.py code generators that produce the `_C_ops` namespace
(python/paddle/_C_ops.py). Here an op is a JAX-traceable function registered
once; `make_op` wraps it with the eager dispatch (tape recording) and
`install_tensor_methods` attaches method variants to Tensor — replacing the
reference's generated pybind methods (paddle/fluid/pybind/eager_method.cc).
"""
from __future__ import annotations

import functools

import jax.numpy as jnp

from ..core.tensor import Tensor, dispatch

OPS = {}            # name -> callable (public op)
TENSOR_METHODS = {}  # method name -> callable


def make_op(name, fn, nondiff_args=(), doc=None):
    @functools.wraps(fn)
    def op(*args, **kwargs):
        return dispatch(fn, *args, name=name, nondiff_args=nondiff_args, **kwargs)

    op.__name__ = name
    op.__qualname__ = name
    if doc:
        op.__doc__ = doc
    OPS[name] = op
    return op


def register(name, *, method=None, nondiff_args=()):
    """Decorator form. ``method``: also expose as Tensor method (True→same name)."""

    def deco(fn):
        op = make_op(name, fn, nondiff_args=nondiff_args)
        if method:
            TENSOR_METHODS[name if method is True else method] = op
        return op

    return deco


def register_direct(name, fn, *, method=None):
    """Register an already-dispatching callable (custom control flow inside)."""
    OPS[name] = fn
    if method:
        TENSOR_METHODS[name if method is True else method] = fn
    return fn


# ops that also get an `x.<name>_()` in-place variant (reference: paddle's
# generated *_ inplace APIs, paddle/phi/api/yaml/ops.yaml inplace entries).
# JAX arrays are immutable, so "in-place" = compute + rebind the wrapper's
# value (Tensor.set_value); the recorded tape keeps functional semantics.
INPLACE_OPS = ("add", "subtract", "multiply", "divide", "scale", "clip",
               "exp", "sqrt", "rsqrt", "reciprocal", "floor", "ceil",
               "round", "trunc", "remainder", "lerp", "pow", "tanh",
               "sigmoid", "relu", "squeeze", "unsqueeze", "flatten",
               "flip", "cast", "reshape", "scatter", "index_add",
               "softmax", "elu")


def install_tensor_methods():
    for mname, op in TENSOR_METHODS.items():
        if not hasattr(Tensor, mname):
            setattr(Tensor, mname, op)

    from ..core.tensor import unwrap, wrap

    def mk_inplace(op):
        def method(self, *args, **kwargs):
            # run the op on a SNAPSHOT carrying the pre-mutation tape
            # identity: the new node's parent must be the old value, not
            # the rebound self (self-referential parent would cut the
            # upstream graph out of backward)
            old_node, old_idx = self._node, self._out_index
            snapshot = wrap(unwrap(self),
                            stop_gradient=self.stop_gradient)
            snapshot._node = old_node
            snapshot._out_index = old_idx
            out = op(snapshot, *args, **kwargs)
            # adopt the output tensor wholesale: raw value (cast_/
            # squeeze_ legally change dtype/shape) AND the tape node
            self._value = unwrap(out)
            if isinstance(out, Tensor):
                self._node = out._node
                self._out_index = out._out_index
                self.stop_gradient = out.stop_gradient
                # hooks must survive the inplace rebind and fire on the
                # POST-mutation gradient (paddle semantics: hooks track the
                # tensor, not the node). Two sources: leaf hooks stored on
                # the tensor, and non-leaf hooks on the old node's slot.
                hooks = self._hooks
                self._hooks = None
                if old_node is not None and old_node.out_hooks:
                    moved = old_node.out_hooks.pop(old_idx, None)
                    if moved:
                        # keep list identity where possible so existing
                        # _HookHandles still remove from the live list
                        if hooks:
                            hooks.extend(moved)
                        else:
                            hooks = moved
                if hooks and self._node is not None:
                    if self._node.out_hooks is None:
                        self._node.out_hooks = {}
                    slot = self._node.out_hooks.get(self._out_index)
                    if slot is None:
                        # reuse the list so existing _HookHandles still
                        # remove from the live collection
                        self._node.out_hooks[self._out_index] = hooks
                    else:
                        slot.extend(hooks)
                elif hooks:
                    self._hooks = hooks
            return self
        return method

    for name in INPLACE_OPS:
        op = OPS.get(name)
        if op is not None and not hasattr(Tensor, name + "_"):
            setattr(Tensor, name + "_", mk_inplace(op))

    def zero_(self):
        # constant rebind: detach from the tape (backprop through the old
        # producer would be wrong — the value no longer depends on it)
        self._value = jnp.zeros_like(self._value)
        self._node = None
        self._out_index = 0
        return self

    def fill_(self, value):
        self._value = jnp.full_like(self._value, value)
        self._node = None
        self._out_index = 0
        return self

    if not hasattr(Tensor, "zero_"):
        Tensor.zero_ = zero_
    if not hasattr(Tensor, "fill_"):
        Tensor.fill_ = fill_
