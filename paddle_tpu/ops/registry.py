"""Op registry: single source of truth for the generated op namespace.

Reference analogue: paddle/phi/api/yaml/ops.yaml + the api_gen.py /
python_c_gen.py code generators that produce the `_C_ops` namespace
(python/paddle/_C_ops.py). Here an op is a JAX-traceable function registered
once; `make_op` wraps it with the eager dispatch (tape recording) and
`install_tensor_methods` attaches method variants to Tensor — replacing the
reference's generated pybind methods (paddle/fluid/pybind/eager_method.cc).
"""
from __future__ import annotations

import functools

from ..core.tensor import Tensor, dispatch

OPS = {}            # name -> callable (public op)
TENSOR_METHODS = {}  # method name -> callable


def make_op(name, fn, nondiff_args=(), doc=None):
    @functools.wraps(fn)
    def op(*args, **kwargs):
        return dispatch(fn, *args, name=name, nondiff_args=nondiff_args, **kwargs)

    op.__name__ = name
    op.__qualname__ = name
    if doc:
        op.__doc__ = doc
    OPS[name] = op
    return op


def register(name, *, method=None, nondiff_args=()):
    """Decorator form. ``method``: also expose as Tensor method (True→same name)."""

    def deco(fn):
        op = make_op(name, fn, nondiff_args=nondiff_args)
        if method:
            TENSOR_METHODS[name if method is True else method] = op
        return op

    return deco


def register_direct(name, fn, *, method=None):
    """Register an already-dispatching callable (custom control flow inside)."""
    OPS[name] = fn
    if method:
        TENSOR_METHODS[name if method is True else method] = fn
    return fn


def install_tensor_methods():
    for mname, op in TENSOR_METHODS.items():
        if not hasattr(Tensor, mname):
            setattr(Tensor, mname, op)
