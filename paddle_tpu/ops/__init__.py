"""The op namespace: paddle-parity tensor ops re-exported flat.

`import paddle_tpu as pt; pt.ops.matmul(...)` — and everything is also
re-exported at the package top level (pt.matmul) plus as Tensor methods,
matching the reference's `paddle.*` / `Tensor.*` dual surface
(python/paddle/tensor/__init__.py tensor_method_func list).
"""
import jax.numpy as jnp

from ..core.tensor import Tensor, dispatch
from . import creation, linalg, manipulation, math, random_ops  # noqa: F401
from .registry import OPS, install_tensor_methods

install_tensor_methods()

# -- operator dunders ---------------------------------------------------------
_b = OPS

Tensor.__add__ = lambda s, o: _b["add"](s, o)
Tensor.__radd__ = lambda s, o: _b["add"](s, o)
Tensor.__sub__ = lambda s, o: _b["subtract"](s, o)
Tensor.__rsub__ = lambda s, o: _b["subtract"](o, s)
Tensor.__mul__ = lambda s, o: _b["multiply"](s, o)
Tensor.__rmul__ = lambda s, o: _b["multiply"](s, o)
Tensor.__truediv__ = lambda s, o: _b["divide"](s, o)
Tensor.__rtruediv__ = lambda s, o: _b["divide"](o, s)
Tensor.__floordiv__ = lambda s, o: _b["floor_divide"](s, o)
Tensor.__mod__ = lambda s, o: _b["mod"](s, o)
Tensor.__pow__ = lambda s, o: _b["pow"](s, o)
Tensor.__rpow__ = lambda s, o: _b["pow"](o, s)
Tensor.__neg__ = lambda s: _b["neg"](s)
Tensor.__abs__ = lambda s: _b["abs"](s)
Tensor.__matmul__ = lambda s, o: _b["matmul"](s, o)
Tensor.__rmatmul__ = lambda s, o: _b["matmul"](o, s)
Tensor.__eq__ = lambda s, o: _b["equal"](s, o)
Tensor.__ne__ = lambda s, o: _b["not_equal"](s, o)
Tensor.__lt__ = lambda s, o: _b["less_than"](s, o)
Tensor.__le__ = lambda s, o: _b["less_equal"](s, o)
Tensor.__gt__ = lambda s, o: _b["greater_than"](s, o)
Tensor.__ge__ = lambda s, o: _b["greater_equal"](s, o)
Tensor.__and__ = lambda s, o: _b["logical_and"](s, o)
Tensor.__or__ = lambda s, o: _b["logical_or"](s, o)
Tensor.__xor__ = lambda s, o: _b["logical_xor"](s, o)
Tensor.__invert__ = lambda s: _b["logical_not"](s)

Tensor.T = property(lambda s: _b["t"](s))
Tensor.mT = property(lambda s: dispatch(lambda v: jnp.swapaxes(v, -1, -2), s))


def __getattr__(name):
    try:
        return OPS[name]
    except KeyError:
        raise AttributeError(f"module 'paddle_tpu.ops' has no op {name!r}") from None


def __dir__():
    return sorted(set(list(globals()) + list(OPS)))
